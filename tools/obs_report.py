"""Render a human-readable run report from obs telemetry artifacts.

Inputs (any combination):

- ``--trace FILE``    span-trace JSONL (serve ``--trace`` / ``TSP_TRACE``)
- ``--series FILE``   a ``bnb_solve.py`` JSON line (or a file of lines —
                      the chunked driver's stdout) whose ``series`` block
                      carries the per-dispatch sampler rows
- ``--metrics FILE``  a ``/metrics.json`` snapshot dump

Output is plain text on stdout: per-trace span trees with durations,
per-column series statistics with a coarse text sparkline, and the top
metric series. No third-party deps, no file writes.

Usage:
    python tools/obs_report.py --trace traces/serve.jsonl
    python tools/obs_report.py --series solve_out.json
    python tools/obs_report.py --trace t.jsonl --series s.json --limit 5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tsp_mpi_reduction_tpu.obs import tracing  # noqa: E402

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(
    values: List[float],
    width: int = 48,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
) -> str:
    """Coarse text sparkline; pass ``lo``/``hi`` to pin the scale (the
    rank heatmap renders every rank against one shared max so row
    heights are comparable)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:  # decimate to the display width, preserving shape
        stride = len(vals) / width
        vals = [vals[int(i * stride)] for i in range(width)]
    lo = min(vals) if lo is None else lo
    hi = max(vals) if hi is None else hi
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1)), len(_SPARK) - 1)]
        for v in vals
    )


def _fmt_attrs(attrs: Dict) -> str:
    if not attrs:
        return ""
    inner = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return f" [{inner}]"


def _render_node(node: Dict, depth: int, out: List[str]) -> None:
    sp = node["span"]
    out.append(
        f"{'  ' * depth}{sp['name']}  {sp['dur_ms']:.2f} ms"
        f"{_fmt_attrs(sp.get('attrs', {}))}"
    )
    for ev in sp.get("events", []):
        out.append(
            f"{'  ' * (depth + 1)}! event {ev['name']}"
            f"{_fmt_attrs(ev.get('attrs', {}))}"
        )
    for child in node["children"]:
        _render_node(child, depth + 1, out)


def render_trace(paths, limit: Optional[int] = None) -> str:
    """Render one or more trace JSONL sinks as span trees. Several paths
    are STITCHED before reconstruction (ISSUE 9: a chunked campaign's
    parent + chunk subprocesses may leave spans across files — the union
    reconstructs as one tree per trace_id, exactly like a single file)."""
    if isinstance(paths, str):
        paths = [paths]
    for p in paths:
        # every path here was EXPLICITLY named by the caller — a typo'd
        # or never-created sink must be an error, not a healthy-looking
        # "0 spans, 0 orphans" (read_traces' skip-unreadable lenience is
        # for programmatic stitching, where sinks may legitimately be
        # partial)
        if not os.path.exists(p):
            raise OSError(f"trace sink not found: {p!r}")
    spans = tracing.read_traces(list(paths))
    trees = tracing.build_trees(spans)
    orphans = tracing.orphan_spans(spans)
    label = ", ".join(paths)
    out: List[str] = [
        f"== trace {label}: {len(spans)} spans, {len(trees)} traces, "
        f"{len(orphans)} orphans =="
    ]
    items = sorted(
        trees.items(),
        key=lambda kv: min(
            (n["span"]["ts"] for n in kv[1]["roots"]), default=0.0
        ),
    )
    shown = items if limit is None else items[:limit]
    for trace_id, tree in shown:
        out.append(f"- trace {trace_id}")
        for root in tree["roots"]:
            _render_node(root, 1, out)
        for orphan in tree["orphans"]:
            out.append(
                f"  ?? ORPHAN {orphan['name']} "
                f"(parent {orphan.get('parent_id')} missing)"
            )
    if limit is not None and len(items) > limit:
        out.append(f"... {len(items) - limit} more traces")
    return "\n".join(out)


def render_series(path: str) -> str:
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            series = doc.get("series") if isinstance(doc, dict) else None
            if not series or not series.get("rows"):
                continue
            cols, rows = series["columns"], series["rows"]
            name = doc.get("instance", "?")
            out.append(
                f"== series {path} [{name}]: {series['samples_total']} "
                f"samples ({series['samples_dropped']} rolled off) =="
            )
            by_col = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
            for col in cols:
                vals = [v for v in by_col[col] if v is not None]
                if not vals:
                    out.append(f"  {col:>16}: (no finite samples)")
                    continue
                out.append(
                    f"  {col:>16}: min {min(vals):.3f}  "
                    f"mean {sum(vals) / len(vals):.3f}  max {max(vals):.3f}  "
                    f"{_sparkline(by_col[col])}"
                )
    if not out:
        out.append(f"== series {path}: no series block found ==")
    return "\n".join(out)


def render_ranks(path: str) -> str:
    """Render a driver payload's ``rank_series`` (ISSUE 10): per-rank
    totals, the imbalance/straggler verdict from ``obs.rank_balance``,
    and an occupancy heatmap (one sparkline row per rank, all rows
    normalized against the same global max so height is comparable
    across ranks — a starved rank reads as a flat-bottom row).

    A payload WITHOUT a rank series — a single-rank run, or
    ``TSP_OBS=off`` — is an error, not an empty section: the caller
    explicitly asked for rank attribution, and rendering a
    healthy-looking nothing would hide that the run never produced it
    (same posture as the missing ``--trace`` sink)."""
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            series = doc.get("rank_series") if isinstance(doc, dict) else None
            if not series or not series.get("rows"):
                continue
            cols, rows = series["columns"], series["rows"]
            ranks = int(series["ranks"])
            name = doc.get("instance", "?")
            out.append(
                f"== ranks {path} [{name}]: {ranks} ranks x "
                f"{series['samples_total']} windows (window "
                f"{series['window']}, {series['samples_dropped']} rolled "
                "off) =="
            )
            bal = (doc.get("obs") or {}).get("rank_balance")
            if bal:
                out.append(
                    f"  balance: nodes_cv {bal['nodes_cv']}  "
                    f"occupancy_cv {bal['occupancy_cv']}  "
                    f"straggler rank {bal['straggler_rank']} "
                    f"(score {bal['straggler_score']})  "
                    f"starved {bal['starved_ranks']} "
                    f"({bal['starvation_episodes']} episodes)"
                )
            i_occ = cols.index("occupancy")

            def _tot(bal_key, col):
                # whole-run totals come from the AUTHORITATIVE balance
                # block when present; the ring rows only cover what the
                # ring still holds, so summing them under-reports any
                # run long enough to roll samples off
                if bal and bal_key in bal:
                    return [int(v) for v in bal[bal_key]]
                i = cols.index(col)
                return [sum(r[i][rk] for r in rows) for rk in range(ranks)]

            node_tot = _tot("nodes_per_rank", "nodes")
            ev_tot = _tot("spill_events_per_rank", "spill_events")
            bh_tot = _tot("spill_bytes_to_host_per_rank", "spill_to_host")
            bd_tot = _tot("spill_bytes_to_device_per_rank", "spill_to_device")
            total = max(sum(node_tot), 1)
            for rk in range(ranks):
                out.append(
                    f"  rank {rk}: nodes {node_tot[rk]} "
                    f"({node_tot[rk] / total * 100:.1f}%)  "
                    f"spill {ev_tot[rk]} ev / {bh_tot[rk]} B down / "
                    f"{bd_tot[rk]} B up"
                )
            # the heatmap: per-rank occupancy over time, shared scale
            occ = [[r[i_occ][rk] for r in rows] for rk in range(ranks)]
            hi = max((v for row in occ for v in row), default=0) or 1
            out.append("  occupancy heatmap (time ->):")
            for rk in range(ranks):
                out.append(f"    rank {rk} {_sparkline(occ[rk], lo=0, hi=hi)}")
    if not out:
        raise ValueError(
            f"no rank_series block in {path!r} — single-rank runs (and "
            "TSP_OBS=off runs) carry no per-rank telemetry; re-run with "
            "--ranks >= 2 and TSP_OBS=on to produce one"
        )
    return "\n".join(out)


def render_balance(path: str) -> str:
    """Render a driver payload's ``obs.balance`` block (ISSUE 15): the
    adaptive controller's per-round decisions as a run-length timeline,
    moved rows/bytes, and the occupancy-CV trajectory as a sparkline.

    A payload WITHOUT the block — a single-device solve, or one from a
    build predating the controller — is an error (exit 2), not an empty
    section: the caller explicitly asked for balance evidence, and a
    healthy-looking nothing would hide that the run never produced it
    (same posture as --ranks)."""
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            bal = (doc.get("obs") or {}).get("balance") if isinstance(
                doc, dict
            ) else None
            if not bal:
                continue
            name = doc.get("instance", "?")
            out.append(
                f"== balance {path} [{name}]: mode {bal['mode']} "
                f"(base {bal['base']}), {bal['ranks']} ranks, "
                f"k {bal['k']}, t_slots {bal['t_slots']} =="
            )
            mix = ", ".join(
                f"{a}: {c}" for a, c in sorted(bal["actions"].items())
            ) or "none"
            out.append(
                f"  decisions: {mix}  (collective dispatches "
                f"{bal['collective_dispatches']}, switches "
                f"{bal['switches']}, steal degraded "
                f"{bal['steal_degraded']}, alive probes "
                f"{bal['alive_probes']})"
            )
            out.append(
                f"  moved: {bal['moved_rows_total']} rows / "
                f"{bal['moved_bytes_total']} B  cv last "
                f"{bal['cv_last']} max {bal['cv_max']}"
            )
            rows = bal.get("rows") or []
            if rows:
                dropped = int(bal.get("rows_dropped", 0))
                suffix = f" ({dropped} rolled off)" if dropped else ""
                out.append(f"  cv trajectory ({len(rows)} rounds{suffix}):")
                out.append(f"    {_sparkline([r[2] for r in rows], lo=0)}")
                # run-length decision timeline: "pair x12 -> skip x40 ..."
                runs: List[List] = []
                for r in rows:
                    if runs and runs[-1][0] == r[1]:
                        runs[-1][1] += 1
                    else:
                        runs.append([r[1], 1])
                out.append(
                    "  timeline: "
                    + " -> ".join(f"{a} x{c}" for a, c in runs)
                )
    if not out:
        raise ValueError(
            f"no obs.balance block in {path!r} — only sharded solves "
            "carry the balance controller; re-run tools/bnb_solve.py "
            "with --ranks >= 1 on a build with the adaptive controller"
        )
    return "\n".join(out)


def render_fleet(path: str) -> str:
    """Render a fleet front's stats line (ISSUE 11): per-replica state +
    last scrape totals, supervision totals (restarts / re-dispatches /
    degraded answers / suppressed duplicates), the shared disk cache
    tier, and fleet-level SLO attainment.

    A payload WITHOUT a ``fleet`` block is an error (exit 2), not an
    empty section — the caller explicitly asked for fleet attribution,
    and a plain serve stats line carries none (same posture as the
    missing ``--trace`` sink and the rank-less ``--ranks``)."""
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            fleet = doc.get("fleet") if isinstance(doc, dict) else None
            if not fleet:
                continue
            out.append(
                f"== fleet {path}: {fleet.get('replica_count', '?')} replicas "
                f"({fleet.get('alive', '?')} alive), "
                f"{doc.get('responses', 0)} responses, "
                f"{doc.get('errors', 0)} errors =="
            )
            out.append(
                f"  supervision: restarts {fleet.get('restarts_total', 0)}  "
                f"redispatches {fleet.get('redispatches_total', 0)}  "
                f"duplicates suppressed {fleet.get('duplicates_suppressed', 0)}"
            )
            degraded = fleet.get("degraded_answers", {})
            out.append(
                "  degraded answers: "
                + (
                    "  ".join(f"{k} {v}" for k, v in sorted(degraded.items()))
                    or "none"
                )
            )
            for row in fleet.get("replicas", []):
                scrape = row.get("scrape") or {}
                scrape_txt = (
                    "  ".join(f"{k} {v}" for k, v in sorted(scrape.items()))
                    if scrape
                    else "(no scrape)"
                )
                out.append(
                    f"  replica {row.get('index')}: pid {row.get('pid')}  "
                    f"{'alive' if row.get('alive') else 'DOWN'}  "
                    f"gen {row.get('generation')}  "
                    f"restarts {row.get('restarts')}  "
                    f"dispatched {row.get('dispatched')}  "
                    f"answered {row.get('answered')}  "
                    f"scrape: {scrape_txt}"
                )
            shared = fleet.get("shared_cache")
            if shared:
                out.append(
                    "  shared cache: "
                    + "  ".join(f"{k} {v}" for k, v in sorted(shared.items()))
                )
            slo = doc.get("slo") or {}
            for tier in sorted(slo):
                row = slo[tier]
                if not isinstance(row, dict) or row.get("attainment") is None:
                    continue
                verdict = "ok" if row.get("ok") else "MISSED"
                out.append(
                    f"  slo {tier}: attainment {row['attainment']:.4f} "
                    f"(goal {row.get('goal')}, target "
                    f"{row.get('target_ms')} ms)  burn "
                    f"{row.get('burn_rate')}  {verdict}"
                )
    if not out:
        raise ValueError(
            f"no fleet block in {path!r} — this renderer reads the fleet "
            "front's stats JSON (python -m tsp_mpi_reduction_tpu fleet "
            "--stats); a plain serve stats line carries no per-replica "
            "attribution"
        )
    return "\n".join(out)


def render_serve(path: str) -> str:
    """Render a serve stats line's admission/preemption block (ISSUE 13):
    per-tier SLO burn rate, the iteration-level loop's preemption /
    resume / shed counters, flush-cause mix, and queue-age percentiles.

    A payload WITHOUT an ``admission`` block is an error (exit 2), not an
    empty section — the caller explicitly asked for admission-control
    attribution, and a pre-iteration-level stats line (or a hand-rolled
    JSON) carries none (same posture as ``--ranks`` / ``--fleet``)."""
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            adm = doc.get("admission") if isinstance(doc, dict) else None
            if not adm:
                continue
            sched = doc.get("scheduler") or {}
            out.append(
                f"== serve {path}: {doc.get('responses', 0)} responses, "
                f"{doc.get('errors', 0)} errors, "
                f"{doc.get('deadline_misses', 0)} deadline misses =="
            )
            burn = adm.get("burn", {})
            for tier in sorted(burn):
                row = burn[tier]
                if not isinstance(row, dict):
                    continue
                b = row.get("burn_rate")
                burn_txt = f"{b:.3f}" if isinstance(b, (int, float)) else (
                    "n/a (below min_count)"
                )
                out.append(
                    f"  burn {tier}: requests {row.get('requests', 0)}  "
                    f"burn rate {burn_txt}"
                )
            out.append(
                f"  preemption: jobs {sched.get('bnb_jobs', 0)}  "
                f"slices {sched.get('bnb_slices', 0)}  "
                f"preemptions {adm.get('preemptions', 0)}  "
                f"resumes {adm.get('resumes', 0)}"
            )
            out.append(
                f"  admission: admit flushes {adm.get('admit_flushes', 0)}  "
                f"slo sheds {adm.get('slo_sheds', 0)}  "
                f"flush causes full {sched.get('full_flushes', 0)} / "
                f"wait {sched.get('wait_flushes', 0)} / "
                f"admit {sched.get('admit_flushes', 0)}"
            )
            qage = adm.get("queue_age_s") or {}
            if qage.get("count"):
                pct = "  ".join(
                    f"{q} {qage[q] * 1000:.1f} ms"
                    for q in ("p50", "p90", "p99")
                    if isinstance(qage.get(q), (int, float))
                )
                out.append(
                    f"  queue age: count {qage['count']}  {pct}"
                )
            else:
                out.append("  queue age: (no flushed tickets)")
    if not out:
        raise ValueError(
            f"no admission block in {path!r} — this renderer reads the "
            "serve stats JSON (SolveService.stats_json / the serve CLI's "
            "--stats line); payloads from before the iteration-level "
            "scheduler carry no admission-control attribution"
        )
    return "\n".join(out)


def render_metrics(path: str, top: int = 20) -> str:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    out: List[str] = [f"== metrics {path}: {len(data)} metrics =="]
    for name in sorted(data):
        m = data[name]
        out.append(f"  {name} ({m['kind']})")
        for entry in m["series"][:top]:
            labels = ",".join(f"{k}={v}" for k, v in sorted(entry["labels"].items()))
            if "hist" in entry:
                h = entry["hist"]
                mean = h["sum"] / h["count"] if h["count"] else 0.0
                val = f"count {h['count']}  mean {mean:.4f}s"
            else:
                val = f"{entry['value']:g}"
            out.append(f"    {{{labels}}} {val}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render obs trace/series/metrics artifacts as text"
    )
    ap.add_argument("--trace", default=None, action="append",
                    help="span JSONL path (repeatable: several sinks are "
                    "stitched into one reconstruction — multi-file "
                    "campaign traces)")
    ap.add_argument("--series", default=None,
                    help="bnb_solve JSON (line file ok) with a series block")
    ap.add_argument("--ranks", default=None,
                    help="bnb_solve JSON (line file ok) with a rank_series "
                    "block (sharded runs) — per-rank totals, imbalance "
                    "verdict, occupancy heatmap; errors (exit 2) when the "
                    "payload carries no per-rank telemetry")
    ap.add_argument("--balance", default=None,
                    help="bnb_solve JSON (line file ok) with an "
                    "obs.balance block (sharded runs) — adaptive "
                    "controller decision timeline, moved rows/bytes, CV "
                    "sparkline; errors (exit 2) when the payload carries "
                    "no balance block")
    ap.add_argument("--fleet", default=None,
                    help="fleet front stats JSON (line file ok) — "
                    "per-replica scrape totals, supervision counters, "
                    "shared-cache tier, fleet SLO attainment; errors "
                    "(exit 2) when the payload has no fleet block")
    ap.add_argument("--serve", default=None,
                    help="serve stats JSON (line file ok) — per-tier SLO "
                    "burn, preemption/resume counters, flush-cause mix, "
                    "queue-age percentiles; errors (exit 2) when the "
                    "payload has no admission block")
    ap.add_argument("--metrics", default=None, help="/metrics.json dump")
    ap.add_argument("--limit", type=int, default=None,
                    help="max traces to render")
    args = ap.parse_args(argv)
    if not (
        args.trace or args.series or args.ranks or args.balance
        or args.fleet or args.serve or args.metrics
    ):
        ap.error(
            "give at least one of --trace / --series / --ranks / "
            "--balance / --fleet / --serve / --metrics"
        )
    sections = []
    try:
        if args.trace:
            sections.append(render_trace(args.trace, args.limit))
        if args.series:
            sections.append(render_series(args.series))
        if args.ranks:
            sections.append(render_ranks(args.ranks))
        if args.balance:
            sections.append(render_balance(args.balance))
        if args.fleet:
            sections.append(render_fleet(args.fleet))
        if args.serve:
            sections.append(render_serve(args.serve))
        if args.metrics:
            sections.append(render_metrics(args.metrics))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        print("\n\n".join(sections))
    except BrokenPipeError:
        return 0  # `| head` closed the pipe: normal CLI behavior
    return 0


if __name__ == "__main__":
    sys.exit(main())

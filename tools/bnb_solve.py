"""TSPLIB branch-and-bound driver: nodes/sec + time-to-optimal reporting.

The north-star benchmark surface (BASELINE.json metric: "B&B nodes/sec +
time-to-optimal"). Solves a TSPLIB instance (file path or the embedded
``burma14``) exactly and prints a JSON metrics line.

Usage:
    python tools/bnb_solve.py burma14 [--backend=...] [--ranks=N]
    python tools/bnb_solve.py path/to/berlin52.tsp --time-limit=60
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tsp_mpi_reduction_tpu.obs import tracing as _tracing  # noqa: E402
from tsp_mpi_reduction_tpu.perf import compile_cache as _perf_cache  # noqa: E402
from tsp_mpi_reduction_tpu.resilience import health as _health  # noqa: E402
from tsp_mpi_reduction_tpu.utils import reporting as _reporting  # noqa: E402
from tsp_mpi_reduction_tpu.utils.backend import select_backend  # noqa: E402


def result_payload(res, inst, args) -> dict:
    """The driver's one-line JSON metrics payload — split out of main()
    so its schema is directly testable (tests/test_obs.py golden-schema
    suite) and reusable by the obs bench leg. ``args`` needs the solver
    config attributes (ranks/bound/mst_kernel/step_kernel/push_order/
    push_block/balance); any argparse.Namespace-alike works."""
    opt = inst.known_optimum
    return {
        "instance": inst.name,
        "dimension": inst.dimension,
        "cost": res.cost,
        "known_optimum": opt,
        "optimal": (res.cost == opt) if opt is not None else None,
        "proven_optimal": res.proven_optimal,
        "nodes_expanded": res.nodes_expanded,
        "nodes_per_sec": round(res.nodes_per_sec, 1),
        "time_to_best_s": round(res.time_to_best, 4),
        "wall_s": round(res.wall_seconds, 3),
        "setup_s": round(res.setup_seconds, 3),
        "setup_ascent_s": round(res.ascent_seconds, 3),
        "setup_ils_s": round(res.ils_seconds, 3),
        # end-to-end time-to-optimal: bound construction + ILS
        # incumbent setup + search (root-closure instances do ~all
        # their work in setup, so wall alone would flatter them)
        "time_to_proof_s": (
            round(res.setup_seconds + res.wall_seconds, 3)
            if res.proven_optimal
            else None
        ),
        "ranks": args.ranks,
        # per-rank expansion counts (sharded runs): the
        # load-balance evidence for the multi-rank engine
        "nodes_per_rank": (
            [int(x) for x in res.nodes_per_rank]
            if res.nodes_per_rank is not None
            else None
        ),
        "bound": args.bound,
        "mst_kernel": args.mst_kernel,
        "step_kernel": getattr(args, "step_kernel", "reference"),
        "push_order": args.push_order,
        "push_block": args.push_block,
        "balance": args.balance if args.ranks > 1 else None,
        "root_lower_bound": round(res.root_lower_bound, 3),
        # final certified LB (min over still-open nodes; = cost when
        # proven) — the honest gap after the search, not the root's.
        # lb_raw is THIS chunk's un-clamped value; lb_certified (==
        # lower_bound) is clamped to the running max carried through
        # the checkpoint, so it is monotone across chunked resumes
        "lower_bound": round(res.lower_bound, 3),
        "lb_raw": (
            round(res.lower_bound_raw, 3)
            if res.lower_bound_raw > -1e30
            else None
        ),
        "lb_certified": round(res.lower_bound, 3),
        "gap": (
            round(res.cost - res.lower_bound, 3)
            if res.lower_bound > -1e30
            else None
        ),
        # reservoir transfer accounting (SpillStats): proof that
        # spills move live-prefix bytes only, measured not asserted
        "spill_rounds": res.spill_rounds,
        "spill_events": res.spill_events,
        "spill_full_merges": res.spill_full_merges,
        "spill_bytes_to_host": res.spill_bytes_to_host,
        "spill_bytes_to_device": res.spill_bytes_to_device,
        # self-healing telemetry (resilience.health): retries
        # absorbed at the spill seam, corrupt checkpoints skipped
        # in favor of older rotation snapshots, injected faults
        "health": _health.HEALTH.snapshot(),
        # compile-once telemetry (perf.compile_cache): AOT store
        # hits/misses, compile seconds paid vs saved, ascent-memo
        # hits — the warm-start evidence per chunk process
        "compile_cache": _perf_cache.stats_dict(),
        # per-dispatch time series (obs.timeseries): nodes/sec,
        # frontier occupancy, spill bytes, incumbent/LB-floor
        # trajectory; null under TSP_OBS=off
        "series": res.series,
        # stall-sentinel verdicts (obs.anomaly): nodes/sec collapse,
        # certified-LB stagnation, rank starvation — each was also fired
        # as a health event at detection time; null under TSP_OBS=off
        "anomalies": res.anomalies,
        # rank-resolved telemetry (obs.rankview, ISSUE 10): per-rank
        # occupancy/alive/nodes/reservoir/spill/best-bound windows;
        # null for single-rank solves and under TSP_OBS=off —
        # tools/obs_report.py --ranks renders it (and errors loudly on
        # a payload without it)
        "rank_series": getattr(res, "rank_series", None),
        # obs layer provenance: trace sink (TSP_TRACE), enabled flag,
        # per-entry compile-phase attribution from the metrics registry,
        # plus the rank imbalance accounting (occupancy CV, straggler
        # score, starved ranks) for sharded runs
        "obs": {
            **_reporting.obs_block(trace_path=_tracing.TRACER.path),
            "rank_balance": getattr(res, "rank_balance", None),
            # adaptive balance controller accounting (ISSUE 15):
            # per-round decisions, moved rows/bytes, CV trajectory —
            # present (not null) even under TSP_OBS=off for sharded
            # solves; tools/obs_report.py --balance renders it
            "balance": getattr(res, "balance", None),
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "instance",
        help="TSPLIB .tsp path or an embedded instance name "
        "(burma14, ulysses16, ulysses22, eil51, berlin52, kroA100)",
    )
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--ranks", type=int, default=1)
    ap.add_argument("--k", type=int, default=256)
    ap.add_argument("--capacity", type=int, default=1 << 17)
    ap.add_argument("--inner-steps", type=int, default=32)
    ap.add_argument("--time-limit", type=float, default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--resume", default=None)
    ap.add_argument("--bound", default="one-tree", choices=["one-tree", "min-out"])
    ap.add_argument(
        "--node-ascent", type=int, default=2,
        help="per-node mini-ascent steps on the MST bound (0 disables; "
        "each step costs one more vmapped Prim but prunes harder)",
    )
    ap.add_argument(
        "--device-loop", default="auto", choices=["auto", "on", "off"],
        help="run the whole search as one transfer-free device dispatch "
        "(auto: on for accelerators — required for full speed on the "
        "remote-TPU relay, whose dispatch degrades after any "
        "device->host readback)",
    )
    ap.add_argument("--max-iters", type=int, default=200_000)
    ap.add_argument(
        "--mst-kernel", default="prim",
        choices=["prim", "boruvka", "prim_pallas"],
        help="MST bound kernel: prim (sequential jnp chain, the default), "
        "prim_pallas (the same chain fused into one Pallas kernel — 3.9x "
        "the bound-eval rate on a v5e; MST ties may resolve differently "
        "under compiled Mosaic argmin, changing node counts but never the "
        "certified value), or boruvka (log-depth batched rounds — the "
        "recorded negative result); all certify the identical bound value",
    )
    ap.add_argument(
        "--step-kernel", default="reference", choices=["reference", "fused"],
        help="expansion-step push kernel: reference (XLA candidate-block "
        "materialize + compacting gather + block write) or fused "
        "(ops.expand_pallas — one Pallas kernel builds and stores pushed "
        "child rows in place; the candidate block never materializes). "
        "Bit-identical results; fused runs in interpret mode off-TPU",
    )
    ap.add_argument(
        "--push-order", default="best-first", choices=["best-first", "natural"],
        help="per-step push ordering: best-first (two-level sort, stack "
        "top = best child) or natural (no sort: cheaper steps but the "
        "tree can grow when the incumbent improves mid-search; same "
        "certified optimum either way)",
    )
    ap.add_argument(
        "--push-block", type=int, default=0,
        help="cap the per-step push block write at this many rows "
        "(lax.cond full-block fallback keeps exactness; 0 = always the "
        "full k*n block)",
    )
    ap.add_argument(
        "--balance", default="pair",
        choices=["pair", "ring", "steal", "adaptive"],
        help="sharded load-balance scheme: pair (richest donates to "
        "poorest each round — O(1) flattening), ring (successor "
        "donation, the r4 scheme), steal (one-collective global "
        "repartition), or adaptive (telemetry-driven skip/pair/steal "
        "per round with hysteresis — ISSUE 15)",
    )
    ap.add_argument(
        "--reorder-every", type=int, default=0,
        help="every N expansion steps, re-sort the stack best-bound-first "
        "(raises the certified LB on gap-reporting runs; 0 = pure DFS)",
    )
    args = ap.parse_args()

    platform = select_backend(args.backend)
    from tsp_mpi_reduction_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache(platform)
    if platform == "cpu" and args.ranks > 1:
        # CPU can host an arbitrary virtual mesh — provision one device per
        # requested rank (the conftest trick, SURVEY.md §4). Keyed on the
        # RESOLVED platform so --backend=auto works on CPU-only hosts; safe
        # here because no jax op has initialized the backend yet.
        from tsp_mpi_reduction_tpu.utils.backend import force_host_platform

        force_host_platform(args.ranks)

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    # one resolver shared with tools/bnb_chunked.py — "random:N[:SEED]"
    # specs (e.g. the BASELINE stretch config "random:200"), embedded
    # names, and TSPLIB paths all go through tsplib.resolve_instance
    try:
        inst = tsplib.resolve_instance(args.instance)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read instance: {e}", file=sys.stderr)
        return 2
    d = inst.distance_matrix()

    # one root span per solve when a trace sink is configured
    # (TSP_TRACE=path.jsonl). Under a TSP_TRACE_PARENT stamp (the chunked
    # driver sets one per chunk subprocess) this root attaches to the
    # campaign's span tree instead of starting a trace island — one
    # campaign, one tree, compile phases and fault events included
    with _tracing.span(
        "bnb.solve",
        parent=_tracing.parent_from_env(),
        instance=inst.name,
        ranks=args.ranks,
    ):
        if args.ranks > 1:
            from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh

            res = bb.solve_sharded(
                d,
                make_rank_mesh(args.ranks),
                capacity_per_rank=args.capacity // args.ranks,
                k=args.k,
                inner_steps=args.inner_steps,
                time_limit_s=args.time_limit,
                max_iters=args.max_iters,
                bound=args.bound,
                node_ascent=args.node_ascent,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                resume_from=args.resume,
                device_loop={"auto": None, "on": True, "off": False}[args.device_loop],
                reorder_every=args.reorder_every,
                mst_kernel=args.mst_kernel,
                balance=args.balance,
                push_order=args.push_order,
                push_block=args.push_block,
                step_kernel=args.step_kernel,
            )
        else:
            res = bb.solve(
                d,
                capacity=args.capacity,
                k=args.k,
                inner_steps=args.inner_steps,
                time_limit_s=args.time_limit,
                max_iters=args.max_iters,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                resume_from=args.resume,
                bound=args.bound,
                node_ascent=args.node_ascent,
                device_loop={"auto": None, "on": True, "off": False}[args.device_loop],
                reorder_every=args.reorder_every,
                mst_kernel=args.mst_kernel,
                push_order=args.push_order,
                push_block=args.push_block,
                step_kernel=args.step_kernel,
            )

    print(json.dumps(result_payload(res, inst, args)))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-stage attribution of the B&B expansion step (VERDICT r3 item 4).

Times, on the live backend, with the same transfer-free chained-dispatch
method as bench.py (one readback per process — the remote-TPU relay
permanently degrades dispatch latency after a process's first
device->host transfer, so every component child gets its own process):

    full_prim / full_boruvka  - _expand_loop, MST re-bound on (the real
                                engine step, per MST kernel)
    nomst                     - _expand_loop with use_mst=False: pop +
                                child materialization + two-level sort +
                                scatter push, no MST chain
    bound_prim / bound_boruvka- _batched_mst_bound alone on a fixed
                                popped batch (the MST chain in isolation)

`full - nomst ~= bound` closes the attribution; the residual is fusion
overlap. Warmup executions drain into the first timed window (the relay's
block_until_ready does not block), so per-dispatch times carry a <=1/M
overstatement — same documented bias as bench.py's timed().

Usage:
    python tools/step_profile.py [eil51] [--k=1024] [--node-ascent=2]
Writes STEP_PROFILE.json (one object, all components).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

COMPONENTS = ("full_prim", "full_boruvka", "nomst", "bound_prim", "bound_boruvka")

#: `guarded` times _guarded_expand_steps (the _solve_device loop body:
#: per-step compaction cond + full-stop cond around the expansion) —
#: `guarded - full_prim` attributes the guard machinery itself
EXTRA_COMPONENTS = ("guarded",)

#: finer-grained slices of the nomst step (--fine): each adds one stage
#: on top of the previous, so successive differences attribute the step
#: (stages mirror the PACKED-frontier push — the round-4 layout):
#:   popgather - packed-row pop gather + unvis + child cost/bound/mask/
#:               path materialization (no sort, no scatter)
#:   sort      - popgather + the two-level priority argsorts + the
#:               analytic inverse-permutation dest computation
#:   scatter   - the full nomst step body: + the single packed-row
#:               scatter push (== nomst, cross-check)
#: The round-3 six-array SoA layout's numbers (6 scatters 4.5 ms, +order
#: gathers 6.9 ms vs 0.42 ms packed) are in STEP_PROFILE_FINE_TPU.json /
#: SCATTER_PROFILE_TPU.json — the evidence that drove the packed layout.
FINE_COMPONENTS = ("popgather", "sort", "scatter")


def child(args) -> int:
    comp = os.environ["TSP_PROFILE_COMPONENT"]
    from tsp_mpi_reduction_tpu.utils.backend import (
        enable_persistent_cache,
        select_backend,
    )

    platform = select_backend(args.backend)
    enable_persistent_cache(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.embedded(args.instance)
    d = inst.distance_matrix()
    n = d.shape[0]
    k = args.k
    na = args.node_ascent
    capacity = max(1 << 17, 8 * k * (n - 1))
    dev = jax.devices()[0]

    # host-only setup (nothing may touch the device before the chain)
    bd = bb._bound_setup(d, "one-tree", node_ascent=na, ascent="host")
    integral = bd.integral
    d64 = np.asarray(d, np.float64)
    tour = bb.nearest_neighbor_tour(d64)
    inc_cost = jnp.asarray(bb.tour_cost(d64, tour), jnp.float32)
    inc_tour = jnp.asarray(tour, jnp.int32)
    fr = bb.make_root_frontier(n, capacity, np.asarray(bd.min_out, np.float64))
    d32 = jnp.asarray(d, jnp.float32)

    kern = "boruvka" if comp.endswith("boruvka") else "prim"
    if args.mst_kernel:
        if args.mst_kernel not in bb._MST_CONN:
            print(
                f"--mst-kernel={args.mst_kernel!r} is not one of "
                f"{sorted(bb._MST_CONN)}", file=sys.stderr,
            )
            return 2
        kern = args.mst_kernel  # e.g. prim_pallas (overrides the default)
    use_mst = comp not in ("nomst",) + FINE_COMPONENTS

    # warm: advance the root frontier to a realistic mid-search state
    # (device-resident, no readback)
    fr, inc_cost, inc_tour, _ = bb._expand_loop(
        fr, inc_cost, inc_tour, d32, bd.min_out, bd.bound_adj, bd.dbar,
        bd.pi, bd.slack, bd.ascent_step, bd.lam_budget, k, n,
        args.warm_steps, integral, True, na, kern,
    )

    if comp in FINE_COMPONENTS:
        # staged replica of the nomst step body. The frontier rides the
        # fori_loop CARRY (as in the real _expand_loop) so XLA gets the
        # same in-place-scatter aliasing opportunity — a loop-invariant
        # frontier would force a copy-on-write of every buffer per
        # iteration and overstate the scatter stages. popgather/sort
        # return the frontier unchanged (they re-pop the same warm state
        # each iteration); the scatter stages evolve it like the real
        # nomst step ('scatter' IS nomst re-derived — its number
        # cross-checks the coarse component). Stage outputs feed the
        # incumbent carry via a min() no-op (values ~1e6-scale, the
        # incumbent ~1e2), so XLA can neither hoist nor dead-code the
        # stage under test. popgather's child arrays are consumed by
        # cheap full reduces, which XLA may fuse without materializing
        # to HBM — read its number as a LOWER bound for that stage.
        # The replica must be kept in sync with _expand_step by hand;
        # it omits the incumbent-TOUR update, the stats reductions and
        # the while_loop's count>0 guard, so 'scatter' undershoots the
        # coarse 'nomst' by those (small, fixed) costs — a known
        # methodological offset in the cross-check, not noise.
        units_per_dispatch = args.steps
        lanes = jnp.arange(k, dtype=jnp.int32)
        cities = jnp.arange(n, dtype=jnp.int32)
        _, word_idx, bit, set_bit = bb._mask_consts(n)
        integral_f = bool(integral)

        w = (n + 31) // 32
        pw = bb._path_words(n)
        kn = k * n

        def stage_once(f, c):
            take = jnp.minimum(f.count, k)
            idx = jnp.maximum(f.count - 1 - lanes, 0)
            live = lanes < take
            p = f.nodes[idx]  # one packed-row gather
            p_pathw = p[:, :pw]  # int8-packed prefix words (layout v2)
            p_mask = p[:, pw : pw + w].astype(jnp.uint32)
            p_depth = p[:, pw + w]
            p_cost = bb._f32(p[:, pw + w + 1]) + c * 0.0  # carry dependency
            p_bound = bb._f32(p[:, pw + w + 2])
            p_sum = bb._f32(p[:, pw + w + 3])
            if integral_f:
                live = live & (p_bound <= c - 1.0)
            else:
                live = live & (p_bound < c)
            cur = bb._path_byte_get(p_pathw, jnp.maximum(p_depth - 1, 0))
            unvis = (p_mask[:, word_idx] >> bit[None, :]) & 1 == 0
            feasible = unvis & live[:, None]
            ccost = p_cost[:, None] + d32[cur]
            cbound = ccost + p_sum[:, None] + bd.bound_adj[None, :]
            cdepth = p_depth[:, None] + 1
            is_complete = (cdepth == n) & feasible
            total = ccost + d32[cities, 0][None, :]
            comp_total = jnp.where(is_complete, total, bb.INF)
            new_inc = jnp.minimum(c, jnp.min(comp_total))
            if integral_f:
                push = feasible & ~is_complete & (cbound <= new_inc - 1.0)
            else:
                push = feasible & ~is_complete & (cbound < new_inc)
            child_mask = p_mask[:, None, :] | set_bit[None, :, :]
            child_sum = p_sum[:, None] - bd.min_out[None, :]
            # packed child path words (the v2 byte-set, as in _expand_step)
            dpos = jnp.minimum(p_depth, n - 1)
            wsel = (dpos // bb.PATH_PACK)[:, None, None]
            shift = ((dpos % bb.PATH_PACK) * 8)[:, None, None]
            pwb = jnp.broadcast_to(p_pathw[:, None, :], (k, n, pw))
            widx = jnp.arange(pw, dtype=jnp.int32)[None, None, :]
            neww = (pwb & ~(0xFF << shift)) | (cities[None, :, None] << shift)
            child_pathw = jnp.where(widx == wsel, neww, pwb)
            if comp == "popgather":
                s = (
                    jnp.sum(jnp.where(push, cbound, 0.0))
                    + jnp.sum(child_pathw).astype(jnp.float32)
                    + jnp.sum(child_mask).astype(jnp.float32)
                    + jnp.sum(child_sum)
                )
                return f, jnp.minimum(new_inc, jnp.abs(s) + 1e6)
            # the two-level priority order + analytic inverse-perm dest
            keys = jnp.where(push, cbound, -bb.INF)
            child_ord = jnp.argsort(-keys, axis=1)
            best_child = jnp.min(jnp.where(push, cbound, bb.INF), axis=1)
            parent_key = jnp.where(
                jnp.isfinite(best_child), best_child, -bb.INF
            )
            parent_ord = jnp.argsort(-parent_key)
            inv_parent = jnp.zeros(k, jnp.int32).at[parent_ord].set(
                jnp.arange(k, dtype=jnp.int32)
            )
            inv_child = jnp.zeros((k, n), jnp.int32).at[
                jnp.arange(k, dtype=jnp.int32)[:, None], child_ord
            ].set(jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (k, n)))
            prio = (inv_parent[:, None] * n + inv_child).reshape(-1)
            flat_push = push.reshape(-1)
            flags_in_order = (
                jnp.zeros(kn, jnp.int32)
                .at[prio]
                .set(flat_push.astype(jnp.int32))
            )
            csum = jnp.cumsum(flags_in_order)
            rank = csum[prio] - 1
            n_push = flat_push.sum()
            base = f.count - take
            if comp == "sort":
                s = (rank[0] + rank[-1] + n_push + base).astype(jnp.float32)
                return f, jnp.minimum(new_inc, jnp.abs(s) + 1e6)
            cand = jnp.concatenate(
                [
                    child_pathw.reshape(-1, pw),
                    child_mask.reshape(-1, w).astype(jnp.int32),
                    jnp.broadcast_to(cdepth, (k, n)).reshape(-1)[:, None],
                    bb._i32(ccost.reshape(-1))[:, None],
                    bb._i32(cbound.reshape(-1))[:, None],
                    bb._i32(child_sum.reshape(-1))[:, None],
                ],
                axis=1,
            )
            # production push: compacting gather + contiguous block write
            f_phys = f.nodes.shape[0]
            f_log = max(f_phys - kn, 1)
            comp_idx = jnp.zeros(kn, jnp.int32).at[
                jnp.where(flat_push, rank, kn)
            ].set(jnp.arange(kn, dtype=jnp.int32), mode="drop")
            block = cand[comp_idx]
            start = jnp.minimum(base, f_phys - kn)
            new_nodes = jax.lax.dynamic_update_slice(
                f.nodes, block, (start, jnp.zeros((), start.dtype))
            )
            new_count = jnp.minimum(base + n_push.astype(jnp.int32), f_log)
            overflow = f.overflow | (base + n_push > f_log)
            return bb.Frontier(new_nodes, new_count, overflow), new_inc

        @jax.jit
        def dispatch(carry):
            _, c = jax.lax.fori_loop(
                0, args.steps, lambda _, fc: stage_once(*fc), (fr, carry)
            )
            return c

    elif comp == "guarded":
        units_per_dispatch = args.steps

        @jax.jit
        def dispatch(carry):
            _, ic2, _, _, _, _ = bb._guarded_expand_steps(
                fr, carry, inc_tour, d32, bd.min_out, bd.bound_adj,
                bd.dbar, bd.pi, bd.slack, bd.ascent_step, bd.lam_budget,
                jnp.asarray(args.steps, jnp.int32), k, n, integral, True,
                na, 0, jnp.asarray(0, jnp.int32), kern,
                "best-first", 0, args.step_kernel,
            )
            return ic2

    elif comp.startswith("full") or comp == "nomst":
        units_per_dispatch = args.steps

        def dispatch(carry):
            # carry = the previous dispatch's incumbent: a true data
            # dependency, so the M dispatches form one chain. The _ref
            # twin (no donation) is REQUIRED here: every dispatch re-pops
            # the same warm frontier, which the production entry would
            # consume on the first call
            _, ic2, _, nodes = bb._expand_loop_ref(
                fr, carry, inc_tour, d32, bd.min_out, bd.bound_adj,
                bd.dbar, bd.pi, bd.slack, bd.ascent_step, bd.lam_budget,
                k, n, args.steps, integral, use_mst, na, kern,
                "best-first", 0, args.step_kernel,
            )
            return ic2

    else:  # bound-only: the popped batch of the warm frontier, repeated
        units_per_dispatch = args.bound_iters
        lanes = jnp.arange(k, dtype=jnp.int32)
        idx = jnp.maximum(fr.count - 1 - lanes, 0)
        p_path = fr.path[idx]
        p_depth = fr.depth[idx]
        p_cost = fr.cost[idx]
        p_mask = fr.mask[idx]
        cur = p_path[lanes, jnp.maximum(p_depth - 1, 0)]
        _, word_idx, bit, _ = bb._mask_consts(n)
        unvis = (p_mask[:, word_idx] >> bit[None, :]) & 1 == 0

        @jax.jit
        def dispatch(carry):
            def body(_, c):
                # optimization_barrier keeps XLA from hoisting the
                # loop-invariant bound evaluation out of the fori chain
                pc = jax.lax.optimization_barrier(p_cost + c * 0.0)
                val = bb._batched_mst_bound(
                    bd.dbar, bd.pi, unvis, cur, pc, n, na,
                    bd.ascent_step, bd.lam_budget, kern,
                )
                return jnp.min(jnp.where(jnp.isfinite(val), val, 1e30))

            return jax.lax.fori_loop(0, args.bound_iters, body, carry)

    t0 = time.perf_counter()
    c = dispatch(inc_cost * 1.0)  # compile + first run, no readback
    jax.block_until_ready(c)  # does not truly block on the relay (bias note)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(args.dispatches):
        c = dispatch(c)
    final = float(c)  # the ONE readback: drains the chain
    wall = time.perf_counter() - t0
    ms_per_unit = wall * 1000.0 / (args.dispatches * units_per_dispatch)
    print(
        json.dumps(
            {
                "component": comp,
                "ms_per_unit": round(ms_per_unit, 4),
                "unit": "bound eval"
                if comp.startswith("bound")
                else "expansion step",
                "dispatches": args.dispatches,
                "units_per_dispatch": units_per_dispatch,
                "compile_s": round(compile_s, 1),
                "final_value": final,
                "device": str(dev),
            }
        )
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("instance", nargs="?", default="eil51")
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--node-ascent", type=int, default=2)
    ap.add_argument("--mst-kernel", default=None,
                    help="override the MST kernel for full_*/bound_*/"
                    "guarded components (e.g. prim_pallas)")
    ap.add_argument("--step-kernel", default="reference",
                    choices=["reference", "fused"],
                    help="expansion push kernel for full_*/guarded "
                    "components: reference (XLA cand block) or fused "
                    "(ops.expand_pallas in-place Pallas push)")
    ap.add_argument("--warm-steps", type=int, default=10)
    ap.add_argument("--steps", type=int, default=10,
                    help="expansion steps per timed dispatch")
    ap.add_argument("--bound-iters", type=int, default=30,
                    help="bound evals per timed dispatch (bound-only)")
    ap.add_argument("--dispatches", type=int, default=12)
    ap.add_argument("--out", default="STEP_PROFILE.json")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of components (any of "
                    "COMPONENTS/FINE_COMPONENTS/EXTRA_COMPONENTS, e.g. "
                    "--only=guarded,full_prim)")
    ap.add_argument("--fine", action="store_true",
                    help="profile the staged slices of the nomst step "
                    "(popgather/sort/scatter) instead of the coarse "
                    "components")
    args = ap.parse_args()

    if "TSP_PROFILE_COMPONENT" in os.environ:
        return child(args)

    results = {}
    if args.only:
        todo = tuple(args.only.split(","))
        bad = set(todo) - set(COMPONENTS + FINE_COMPONENTS + EXTRA_COMPONENTS)
        if bad:
            print(f"unknown components: {sorted(bad)}", file=sys.stderr)
            return 2
    else:
        todo = FINE_COMPONENTS if args.fine else COMPONENTS
    for comp in todo:
        env = dict(os.environ, TSP_PROFILE_COMPONENT=comp)
        try:
            r = subprocess.run(
                [sys.executable] + sys.argv, capture_output=True,
                text=True, env=env, timeout=1800,
            )
        except subprocess.TimeoutExpired:
            print(f"{comp}: subprocess timed out", file=sys.stderr)
            continue
        sys.stderr.write(r.stderr[-2000:])
        try:
            results[comp] = json.loads(r.stdout.strip().splitlines()[-1])
            print(f"{comp}: {results[comp]['ms_per_unit']} ms/"
                  f"{results[comp]['unit']}", file=sys.stderr)
        except (json.JSONDecodeError, IndexError):
            print(f"{comp}: no JSON (rc={r.returncode})", file=sys.stderr)
    if not results:
        return 1
    if args.fine and args.out == "STEP_PROFILE.json":
        args.out = "STEP_PROFILE_FINE.json"  # don't clobber the coarse run
    out = {
        "instance": args.instance,
        "fine": args.fine,
        "k": args.k,
        "node_ascent": args.node_ascent,
        "mst_kernel": args.mst_kernel or "prim (default)",
        "step_kernel": args.step_kernel,
        "method": "chained transfer-free dispatches, one readback per "
        "component subprocess; warmup drains into the first window "
        "(<=1/dispatches overstatement)",
        "components": results,
    }
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    write_json_atomic(args.out, out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())

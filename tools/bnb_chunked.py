"""Long-run B&B driver: chunks of search in FRESH subprocesses.

Why: on this image's remote-TPU relay, a process's first device->host
readback permanently degrades every later dispatch (~65 ms per while-loop
iteration — see models/branch_bound.py). A single process can therefore
run only ONE full-speed device dispatch: the readback that ends chunk 1
would cripple chunk 2. This driver gives every chunk its own process —
`bnb_solve.py --device-loop on` with checkpoint/resume — so each chunk
runs in the relay's fast mode; the persistent compilation cache makes the
per-chunk compile a cache hit after the first.

Usage:
    python tools/bnb_chunked.py kroA100 --chunk-iters=200000 \
        --max-chunks=20 --time-limit=1200 [bnb_solve args passed through]

Prints one JSON line per chunk (bnb_solve's output) and a final summary.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tsp_mpi_reduction_tpu.obs import tracing as _tracing  # noqa: E402


def _ckpt_candidates(ckpt_path: str) -> list:
    """Existing snapshots in the rotation chain, newest first. The gate
    must look PAST the primary path: a writer killed inside the store's
    rotation window (old snapshot already shifted to ``.1``, new one not
    yet renamed in) leaves the primary missing while valid rotation
    snapshots still hold the campaign — treating that as 'no checkpoint'
    would silently restart from scratch."""
    from tsp_mpi_reduction_tpu.resilience import checkpoint as ck

    return [p for p in ck.rotation_paths(ckpt_path) if os.path.exists(p)]


def _verify_resume_fingerprint(ckpt_path: str, instance_spec: str) -> str:
    """Pre-flight for ``--resume-existing``: the checkpoint header carries
    the instance fingerprint (hash of the distance matrix,
    ``resilience.checkpoint``), so a checkpoint from a DIFFERENT instance
    is refused here with a clear error instead of being silently resumed
    (or exploding deep inside a chunk subprocess). Returns "" when the
    resume is safe, else the error message. Legacy headerless checkpoints
    skip the pre-flight — the solver's in-payload fingerprint check still
    guards them in-chunk."""
    from tsp_mpi_reduction_tpu.resilience import checkpoint as ck
    from tsp_mpi_reduction_tpu.utils import tsplib

    header = None
    for cand in _ckpt_candidates(ckpt_path):
        try:
            header = ck.read_header(cand)
            break
        except (ck.CheckpointError, OSError):
            # corrupt/unreadable snapshot: the store's rotation fallback
            # inside the chunk handles it — not a mismatch; try an older
            # candidate's header instead
            continue
    if not header or not header.get("fingerprint"):
        return ""
    try:
        inst = tsplib.resolve_instance(instance_spec)
    except (ValueError, OSError) as e:
        return f"error: cannot resolve instance {instance_spec!r}: {e}"
    want = ck.instance_fingerprint(inst.distance_matrix())
    if header["fingerprint"] != want:
        return (
            f"error: checkpoint {ckpt_path!r} was written for a different "
            f"instance (fingerprint {header['fingerprint']} != {want} for "
            f"{instance_spec!r}) — resuming it would silently continue the "
            "wrong search; point --checkpoint elsewhere or remove the file"
        )
    return ""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("instance")
    ap.add_argument("--chunk-iters", type=int, default=200_000,
                    help="expansion-step budget per chunk (= subprocess)")
    ap.add_argument("--max-chunks", type=int, default=10)
    ap.add_argument("--time-limit", type=float, default=None,
                    help="total wall budget across chunks (seconds)")
    ap.add_argument("--checkpoint", default=None,
                    help="checkpoint path (default: a temp file)")
    ap.add_argument("--resume-existing", action="store_true",
                    help="continue from a pre-existing checkpoint at "
                    "--checkpoint instead of refusing it")
    ap.add_argument("--chunk-timeout", type=float, default=3600.0,
                    help="hard per-chunk wall cap (a lapsed chip grant "
                    "can hang a fresh client init forever)")
    ap.add_argument("--chunk-retries", type=int, default=1,
                    help="re-run a crashed/hung chunk this many times "
                    "before aborting the campaign — the crash-safe "
                    "checkpoint store makes a retry resume from the "
                    "newest valid snapshot, so a killed writer or a "
                    "transient grant hiccup costs one chunk, not the run")
    ap.add_argument("--lb-stall-gain", type=float, default=None,
                    help="stop when the certified lower bound gains less "
                    "than this per chunk, averaged over the last "
                    "--lb-stall-chunks chunks (the run-to-exhaustion stop "
                    "rule: a flattened climb is an answer, not a failure)")
    ap.add_argument("--lb-stall-chunks", type=int, default=5)
    args, passthrough = ap.parse_known_args()
    if args.max_chunks < 1:
        ap.error("--max-chunks must be >= 1")

    ckpt = args.checkpoint or os.path.join(
        tempfile.mkdtemp(prefix="bnb_chunked_"), "chunk.npz"
    )
    ckpt_real = ckpt if ckpt.endswith(".npz") else ckpt + ".npz"
    if _ckpt_candidates(ckpt_real) and not args.resume_existing:
        print(
            f"error: checkpoint {ckpt_real!r} already exists (or its "
            "rotation snapshots do) — a fresh run would silently continue "
            "it; pass --resume-existing to do that intentionally, or "
            "remove the file(s)",
            file=sys.stderr,
        )
        return 2
    if _ckpt_candidates(ckpt_real) and args.resume_existing:
        err = _verify_resume_fingerprint(ckpt_real, args.instance)
        if err:
            print(err, file=sys.stderr)
            return 2
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bnb_solve.py")
    t0 = time.perf_counter()
    last = None
    lb_history: list = []
    stalled = False
    # ONE campaign = ONE span tree (ISSUE 9): the campaign root opens here
    # (itself under TSP_TRACE_PARENT, so campaigns nest under a caller's
    # trace too), each chunk attempt gets a child span in THIS process,
    # and every chunk subprocess inherits that chunk span's context via
    # its env — its bnb.solve root (compile/aot_load phases, fault events,
    # fallback restores included) then attaches instead of orphaning.
    # All spans land in the same TSP_TRACE JSONL (append mode); with no
    # sink configured every span here is the shared no-op.
    campaign_cm = _tracing.span(
        "bnb.campaign",
        parent=_tracing.parent_from_env(),
        instance=args.instance,
        max_chunks=args.max_chunks,
    )
    #: per-chunk compile attribution (obs registry entry labels): each
    #: chunk process reports its OWN compile/aot-load seconds, so the
    #: summary can show which chunk paid the compile and which warmed
    compile_by_chunk: list = []
    child_env = dict(os.environ)
    # warm-start wiring (PR 5 tentpole): every chunk is a fresh process,
    # and the relay REQUIRES that — so give them all ONE compile-cache
    # dir. Chunk 1 populates it (jax persistent cache + AOT executables +
    # the ascent memo); chunk N+1's startup then drops from full-JIT to
    # cache-load. Default: a campaign-local dir next to the checkpoint
    # (self-contained, reaped with it); an explicit TSP_COMPILE_CACHE —
    # including "off" — always wins.
    if "TSP_COMPILE_CACHE" not in child_env:
        child_env["TSP_COMPILE_CACHE"] = os.path.join(
            os.path.dirname(os.path.abspath(ckpt_real)) or ".",
            "compile_cache",
        )
    with campaign_cm as campaign:
        for chunk in range(1, args.max_chunks + 1):
            line = None
            # a failed attempt is re-run, not fatal: the crash-safe store
            # guarantees the checkpoint on disk is the newest VALID snapshot
            # (rotation fallback), so the retry resumes where the crash left
            # recoverable state — cmd is rebuilt per attempt because the
            # first crash may have just created the checkpoint to resume
            for attempt in range(args.chunk_retries + 1):
                # a retry must never overrun the CAMPAIGN wall budget: a hung
                # chunk already burned up to chunk_timeout, so both the
                # bail-out and the subprocess cap track the remaining budget
                chunk_cap = args.chunk_timeout
                if args.time_limit is not None:
                    remaining = args.time_limit - (time.perf_counter() - t0)
                    if remaining <= 0:
                        print(
                            f"chunk {chunk}: wall budget exhausted "
                            "(no retry attempted)", file=sys.stderr,
                        )
                        break
                    chunk_cap = min(chunk_cap, remaining + 30.0)  # grace: JSON flush
                cmd = [
                    sys.executable, tool, args.instance,
                    "--device-loop=on", f"--max-iters={args.chunk_iters}",
                    f"--checkpoint={ckpt}",
                ]
                if _ckpt_candidates(ckpt_real):
                    # the store's restore falls back through the rotation
                    # chain, so --resume is right even when the primary file
                    # itself was lost to a mid-rotation crash
                    cmd.append(f"--resume={ckpt}")
                if args.time_limit is not None:
                    # remaining wall budget is enforced inside the chunk too
                    # (coarsely: between its device dispatches)
                    cmd.append(f"--time-limit={max(remaining, 1.0)}")
                cmd += passthrough
                retry_note = (
                    f" — retrying ({attempt + 1}/{args.chunk_retries})"
                    if attempt < args.chunk_retries
                    else ""
                )
                # one span per ATTEMPT (a retried chunk shows both tries
                # in the tree); its context rides the child's env so the
                # subprocess's bnb.solve root attaches under it
                with _tracing.span(
                    "campaign.chunk", chunk=chunk, attempt=attempt
                ) as csp:
                    parent_token = _tracing.format_parent(csp.context)
                    if parent_token is not None:
                        child_env[_tracing.ENV_PARENT] = parent_token
                    try:
                        r = subprocess.run(
                            cmd, capture_output=True, text=True,
                            timeout=chunk_cap, env=child_env,
                        )
                    except subprocess.TimeoutExpired:
                        csp.set("timeout_s", round(chunk_cap, 1))
                        csp.event("chunk_timeout")
                        print(
                            f"chunk {chunk}: timed out after "
                            f"{chunk_cap:.0f}s{retry_note}",
                            file=sys.stderr,
                        )
                        continue
                    csp.set("rc", r.returncode)
                sys.stderr.write(r.stderr[-2000:])
                out = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
                if r.returncode == 0 and out.startswith("{"):
                    line = out
                    break
                campaign.event("chunk_retry", chunk=chunk, rc=r.returncode)
                print(
                    f"chunk {chunk}: solver failed rc={r.returncode}{retry_note}",
                    file=sys.stderr,
                )
            if line is None:
                return 1
            last = json.loads(line)
            print(line)
            compile_by_chunk.append(
                (last.get("obs") or {}).get("compile_phases_s") or {}
            )
            # a chunk just ran on the backend — later chunks skip the
            # accelerator probe subprocess (each probe is a full jax import
            # plus a chip claim/release cycle: wasted wall and extra exposure
            # to the grant-forfeit failure mode). A mid-run grant lapse is
            # still bounded by --chunk-timeout.
            child_env["TSP_BACKEND_PROBED"] = "1"
            elapsed = time.perf_counter() - t0
            if last["proven_optimal"]:
                break
            if args.time_limit is not None and elapsed > args.time_limit:
                break
            # stall detection tracks the CERTIFIED (monotone) LB: the engine
            # clamps it to the running max carried through the checkpoint, so
            # a chunk whose raw min-over-open regresses (VERDICT r5) can no
            # longer fake negative progress and trip the stall rule early
            lb_cert = last.get("lb_certified", last["lower_bound"])
            if args.lb_stall_gain is not None and lb_cert is not None:
                lb_history.append(float(lb_cert))
                w = args.lb_stall_chunks
                if (
                    len(lb_history) > w
                    and lb_history[-1] - lb_history[-1 - w]
                    < args.lb_stall_gain * w
                ):
                    stalled = True
                    print(
                        f"chunk {chunk}: LB climb flattened "
                        f"(+{lb_history[-1] - lb_history[-1 - w]:.2f} over the "
                        f"last {w} chunks < {args.lb_stall_gain}/chunk) — "
                        "stopping at exhaustion", file=sys.stderr,
                    )
                    break
        assert last is not None
        # defense in depth: the engine already clamps, but the summary's
        # certified LB is additionally the max over every chunk it saw
        lb_final = last.get("lb_certified", last["lower_bound"])
        if lb_history:
            lb_final = max([lb_final] + lb_history) if lb_final is not None else max(lb_history)
        print(json.dumps({
            "summary": True,
            "instance": last["instance"],
            "chunks": chunk,
            "cost": last["cost"],
            "proven_optimal": last["proven_optimal"],
            "lower_bound": lb_final,
            "lb_raw": last.get("lb_raw"),
            "lb_certified": lb_final,
            "gap": (
                round(last["cost"] - lb_final, 3) if lb_final is not None else None
            ),
            "lb_stalled": stalled,
            "total_wall_s": round(time.perf_counter() - t0, 1),
            # compile cost attributed per chunk process (entry-labeled obs
            # registry series, satellite of ISSUE 6): chunk 1 pays, the
            # warm-start chunks show aot_load-only seconds
            "compile_s_by_chunk": compile_by_chunk,
            "compile_s_total": {
                entry: round(sum(c.get(entry, {}).get(ph, 0.0)
                                 for c in compile_by_chunk
                                 for ph in c.get(entry, {})), 4)
                for entry in {e for c in compile_by_chunk for e in c}
            },
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

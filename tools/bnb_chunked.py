"""Long-run B&B driver: chunks of search in FRESH subprocesses.

Why: on this image's remote-TPU relay, a process's first device->host
readback permanently degrades every later dispatch (~65 ms per while-loop
iteration — see models/branch_bound.py). A single process can therefore
run only ONE full-speed device dispatch: the readback that ends chunk 1
would cripple chunk 2. This driver gives every chunk its own process —
`bnb_solve.py --device-loop on` with checkpoint/resume — so each chunk
runs in the relay's fast mode; the persistent compilation cache makes the
per-chunk compile a cache hit after the first.

Usage:
    python tools/bnb_chunked.py kroA100 --chunk-iters=200000 \
        --max-chunks=20 --time-limit=1200 [bnb_solve args passed through]

Prints one JSON line per chunk (bnb_solve's output) and a final summary.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("instance")
    ap.add_argument("--chunk-iters", type=int, default=200_000,
                    help="expansion-step budget per chunk (= subprocess)")
    ap.add_argument("--max-chunks", type=int, default=10)
    ap.add_argument("--time-limit", type=float, default=None,
                    help="total wall budget across chunks (seconds)")
    ap.add_argument("--checkpoint", default=None,
                    help="checkpoint path (default: a temp file)")
    ap.add_argument("--resume-existing", action="store_true",
                    help="continue from a pre-existing checkpoint at "
                    "--checkpoint instead of refusing it")
    ap.add_argument("--chunk-timeout", type=float, default=3600.0,
                    help="hard per-chunk wall cap (a lapsed chip grant "
                    "can hang a fresh client init forever)")
    ap.add_argument("--lb-stall-gain", type=float, default=None,
                    help="stop when the certified lower bound gains less "
                    "than this per chunk, averaged over the last "
                    "--lb-stall-chunks chunks (the run-to-exhaustion stop "
                    "rule: a flattened climb is an answer, not a failure)")
    ap.add_argument("--lb-stall-chunks", type=int, default=5)
    args, passthrough = ap.parse_known_args()
    if args.max_chunks < 1:
        ap.error("--max-chunks must be >= 1")

    ckpt = args.checkpoint or os.path.join(
        tempfile.mkdtemp(prefix="bnb_chunked_"), "chunk.npz"
    )
    ckpt_real = ckpt if ckpt.endswith(".npz") else ckpt + ".npz"
    if os.path.exists(ckpt_real) and not args.resume_existing:
        print(
            f"error: checkpoint {ckpt_real!r} already exists — a fresh run "
            "would silently continue it; pass --resume-existing to do that "
            "intentionally, or remove the file",
            file=sys.stderr,
        )
        return 2
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bnb_solve.py")
    t0 = time.perf_counter()
    last = None
    lb_history: list = []
    stalled = False
    child_env = dict(os.environ)
    for chunk in range(1, args.max_chunks + 1):
        cmd = [
            sys.executable, tool, args.instance,
            "--device-loop=on", f"--max-iters={args.chunk_iters}",
            f"--checkpoint={ckpt}",
        ]
        if os.path.exists(ckpt_real):
            cmd.append(f"--resume={ckpt}")
        if args.time_limit is not None:
            # remaining wall budget is enforced inside the chunk too
            # (coarsely: between its device dispatches)
            remaining = args.time_limit - (time.perf_counter() - t0)
            cmd.append(f"--time-limit={max(remaining, 1.0)}")
        cmd += passthrough
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=args.chunk_timeout, env=child_env,
            )
        except subprocess.TimeoutExpired:
            print(f"chunk {chunk}: timed out after {args.chunk_timeout:.0f}s",
                  file=sys.stderr)
            return 1
        sys.stderr.write(r.stderr[-2000:])
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        if r.returncode != 0 or not line.startswith("{"):
            print(f"chunk {chunk}: solver failed rc={r.returncode}",
                  file=sys.stderr)
            return 1
        last = json.loads(line)
        print(line)
        # a chunk just ran on the backend — later chunks skip the
        # accelerator probe subprocess (each probe is a full jax import
        # plus a chip claim/release cycle: wasted wall and extra exposure
        # to the grant-forfeit failure mode). A mid-run grant lapse is
        # still bounded by --chunk-timeout.
        child_env["TSP_BACKEND_PROBED"] = "1"
        elapsed = time.perf_counter() - t0
        if last["proven_optimal"]:
            break
        if args.time_limit is not None and elapsed > args.time_limit:
            break
        # stall detection tracks the CERTIFIED (monotone) LB: the engine
        # clamps it to the running max carried through the checkpoint, so
        # a chunk whose raw min-over-open regresses (VERDICT r5) can no
        # longer fake negative progress and trip the stall rule early
        lb_cert = last.get("lb_certified", last["lower_bound"])
        if args.lb_stall_gain is not None and lb_cert is not None:
            lb_history.append(float(lb_cert))
            w = args.lb_stall_chunks
            if (
                len(lb_history) > w
                and lb_history[-1] - lb_history[-1 - w]
                < args.lb_stall_gain * w
            ):
                stalled = True
                print(
                    f"chunk {chunk}: LB climb flattened "
                    f"(+{lb_history[-1] - lb_history[-1 - w]:.2f} over the "
                    f"last {w} chunks < {args.lb_stall_gain}/chunk) — "
                    "stopping at exhaustion", file=sys.stderr,
                )
                break
    assert last is not None
    # defense in depth: the engine already clamps, but the summary's
    # certified LB is additionally the max over every chunk it saw
    lb_final = last.get("lb_certified", last["lower_bound"])
    if lb_history:
        lb_final = max([lb_final] + lb_history) if lb_final is not None else max(lb_history)
    print(json.dumps({
        "summary": True,
        "instance": last["instance"],
        "chunks": chunk,
        "cost": last["cost"],
        "proven_optimal": last["proven_optimal"],
        "lower_bound": lb_final,
        "lb_raw": last.get("lb_raw"),
        "lb_certified": lb_final,
        "gap": (
            round(last["cost"] - lb_final, 3) if lb_final is not None else None
        ),
        "lb_stalled": stalled,
        "total_wall_s": round(time.perf_counter() - t0, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

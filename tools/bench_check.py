"""Bench-history regression gate + shared history appender (ISSUE 9).

Two subcommands (default: ``check``):

``check``   read ``bench_history.jsonl``, evaluate every governed metric's
            newest sample against its prior samples (median + MAD model,
            per-metric direction/threshold/min-samples — the governed
            table is ``obs.bench_history.DEFAULT_RULES``; it includes the
            rank-resolved telemetry gates ``shard_rank_obs_overhead`` /
            ``shard_rank_us_per_dispatch`` from ``TSP_BENCH=shard``),
            print a verdict table, exit 1 on any
            regression. Below min-samples a metric reports
            ``insufficient`` and never fails — a fresh clone passes while
            history accretes. ``make bench-check`` runs this and the
            default ``make`` chains it, so a slowdown fails the build
            instead of aging invisibly in a BENCH_*.json.

``append``  turn an existing ``BENCH_*.json`` artifact into one history
            record and append it through the same locked atomic appender
            the in-process benches use — this is how ``tools/tpu_bench.sh``
            joins TPU-grant captures to the same history as CPU runs.

Usage:
    python tools/bench_check.py [check] [--history PATH] [--rules RULES.json] [--json]
    python tools/bench_check.py append BENCH_OBS.json --mode obs [--backend tpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tsp_mpi_reduction_tpu.obs import bench_history as bh  # noqa: E402


def _default_history() -> str:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return bh.resolve_history_path(repo_root) or os.path.join(
        repo_root, bh.DEFAULT_PATH
    )


def run_check(history: str, rules_path: Optional[str], as_json: bool) -> int:
    rules = bh.load_rules(rules_path) if rules_path else None
    records = bh.read(history)
    verdicts = bh.check(records, rules)
    regressions = [v for v in verdicts if v.status == "regression"]
    if as_json:
        print(json.dumps({
            "history": history,
            "records": len(records),
            "verdicts": [v.as_dict() for v in verdicts],
            "regressions": len(regressions),
            "ok": not regressions,
        }))
        return 1 if regressions else 0
    if not records:
        print(
            f"bench-check: no history at {history} — nothing to gate "
            "(run any TSP_BENCH=* bench to start one)"
        )
        return 0
    print(f"bench-check: {len(records)} records in {history}")
    status_mark = {"ok": "ok ", "regression": "FAIL", "insufficient": "n/a ",
                   "no_value": "n/a "}
    for v in verdicts:
        print(
            f"  [{status_mark.get(v.status, '?')}] {v.metric} "
            f"({v.group}, {v.samples} samples): {v.detail or v.status}"
        )
    if regressions:
        print(
            f"bench-check: {len(regressions)} regression(s) — the newest "
            "sample is worse than its history allows; investigate before "
            "shipping (or re-run the bench if the machine was loaded)"
        )
        return 1
    print("bench-check: no regressions")
    return 0


def run_append(
    artifact_path: str, mode: str, history: str, backend: Optional[str]
) -> int:
    try:
        with open(artifact_path, encoding="utf-8") as fh:
            artifact = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read artifact {artifact_path!r}: {e}",
              file=sys.stderr)
        return 2
    if not isinstance(artifact, dict) or artifact.get("metric") is None:
        print(
            f"error: {artifact_path!r} has no 'metric' headline — not a "
            "bench artifact", file=sys.stderr,
        )
        return 2
    record = bh.make_record(
        mode, artifact,
        config={"artifact": os.path.basename(artifact_path)},
        backend=backend or "unknown",
    )
    bh.append(history, record)
    print(f"appended {artifact['metric']}={artifact.get('value')} -> {history}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # default subcommand: bare invocation == check
    if not argv or argv[0].startswith("-"):
        argv.insert(0, "check")
    ap = argparse.ArgumentParser(
        description="bench-history regression gate / history appender"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    chk = sub.add_parser("check", help="gate on the history (default)")
    chk.add_argument("--history", default=None, metavar="PATH")
    chk.add_argument("--rules", default=None, metavar="RULES.json",
                     help="per-metric overrides merged over the defaults")
    chk.add_argument("--json", action="store_true", dest="as_json")
    app = sub.add_parser("append", help="append a BENCH_*.json artifact")
    app.add_argument("artifact")
    app.add_argument("--mode", required=True,
                     help="bench mode that produced the artifact (bnb/serve/...)")
    app.add_argument("--history", default=None, metavar="PATH")
    app.add_argument("--backend", default=None,
                     help="backend label (tpu_bench.sh passes tpu)")
    args = ap.parse_args(argv)
    if args.cmd == "append":
        if args.history is None:
            repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            resolved = bh.resolve_history_path(repo_root)
            if resolved is None:
                # TSP_BENCH_HISTORY=off is the WRITE kill switch: it must
                # silence this append path exactly like the in-process
                # bench appends (check below still gates the existing
                # file — off disables appending, not gating)
                print("history disabled (TSP_BENCH_HISTORY=off): append skipped")
                return 0
            history = resolved
        else:
            history = args.history
        return run_append(args.artifact, args.mode, history, args.backend)
    history = args.history or _default_history()
    return run_check(history, args.rules, args.as_json)


if __name__ == "__main__":
    sys.exit(main())

"""A/B microbenchmark of B&B push (scatter-insert) variants on the live
backend — the fine step profile (STEP_PROFILE_FINE_TPU.json) showed the
push owns ~6.5 ms of the 9.9 ms expansion step (6 scatters ~4.2 ms, the
six [order] re-order gathers ~2.3 ms), so this sizes the fix before it
lands in `_expand_step`.

Variants (identical resulting frontier contents where noted):

  v0_order_scatter   - the current engine push: 6 gathers by `order` +
                       6 scatters at ordered-cumsum dest (baseline)
  v1_invperm_scatter - NO reorder gathers: dest computed per-candidate in
                       unordered space via the analytic inverse of the
                       two-level priority permutation (inv argsorts +
                       1-D flag scatter + cumsum + 1-D gather); then the
                       same 6 row scatters. Bit-identical frontier to v0.
  v2_packed_scatter  - v1 but the six SoA buffers are packed into ONE
                       [cap, n+W+4] i32 buffer (f32 fields bitcast), so
                       the push is ONE row scatter. Tests whether scatter
                       cost is per-op or per-row.
  v3_gather_dus      - compaction by ONE gather of packed rows by `order`
                       + a contiguous dynamic_update_slice of the whole
                       k*n block at the stack top (garbage above n_push
                       is beyond `count`, never read; needs k*n headroom).
                       (The production push since round 4.)
  v4_capped_gather_dus - v3 but the gathered/written block is capped at
                       T = min(4k, k*n) rows instead of the full k*n:
                       typical per-step push counts (~k on eil51) leave
                       ~92% of the k*n block as never-read garbage that
                       the gather+DUS still materializes. The engine
                       version would need a lax.cond fallback to the
                       full block when n_push > T (exactness); here the
                       count is clamped and `capped_events` reports how
                       often the cap would have engaged (0 on the warm
                       eil51 state = the timing is the common-case cost).

Method: same transfer-free chained-dispatch protocol as step_profile.py
(one subprocess per variant, one readback at the end).

NOTE: this experiment drove the round-4 packed-frontier refactor — the
engine's Frontier is now the packed layout itself (v2 is the production
push). v0/v1 reconstruct the round-3 six-array SoA layout locally (as a
script-level namedtuple) so the A/B stays reproducible.

Usage: python tools/scatter_profile.py [eil51] [--k=1024]
Writes SCATTER_PROFILE.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

VARIANTS = ("v0_order_scatter", "v1_invperm_scatter", "v2_packed_scatter",
            "v3_gather_dus", "v4_capped_gather_dus")


def child(args) -> int:
    comp = os.environ["TSP_SCATTER_VARIANT"]
    from tsp_mpi_reduction_tpu.utils.backend import (
        enable_persistent_cache,
        select_backend,
    )

    platform = select_backend(args.backend)
    enable_persistent_cache(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.embedded(args.instance)
    d = inst.distance_matrix()
    n = d.shape[0]
    k = args.k
    capacity = max(1 << 17, 8 * k * (n - 1))
    dev = jax.devices()[0]

    bd = bb._bound_setup(d, "one-tree", node_ascent=2, ascent="host")
    integral = bool(bd.integral)
    d64 = np.asarray(d, np.float64)
    tour = bb.nearest_neighbor_tour(d64)
    inc_cost = jnp.asarray(bb.tour_cost(d64, tour), jnp.float32)
    inc_tour = jnp.asarray(tour, jnp.int32)
    fr = bb.make_root_frontier(n, capacity, np.asarray(bd.min_out, np.float64))
    d32 = jnp.asarray(d, jnp.float32)

    # warm to a realistic mid-search frontier, device-resident
    fr, inc_cost, inc_tour, _ = bb._expand_loop(
        fr, inc_cost, inc_tour, d32, bd.min_out, bd.bound_adj, bd.dbar,
        bd.pi, bd.slack, bd.ascent_step, bd.lam_budget, k, n,
        args.warm_steps, integral, True, 2, "prim",
    )

    from typing import NamedTuple

    class SoAF(NamedTuple):
        """The round-3 six-array SoA frontier layout (v0/v1 baseline)."""

        path: jnp.ndarray
        mask: jnp.ndarray
        depth: jnp.ndarray
        cost: jnp.ndarray
        bound: jnp.ndarray
        sum_min: jnp.ndarray
        count: jnp.ndarray
        overflow: jnp.ndarray

    # materialized copies of the warm frontier's logical fields ("+ 0"
    # forces real buffers, not lazy views)
    soa_fr = SoAF(
        fr.path + 0, fr.mask + 0, fr.depth + 0, fr.cost + 0.0,
        fr.bound + 0.0, fr.sum_min + 0.0, fr.count, fr.overflow,
    )

    f_cap = fr.path.shape[0]
    W = fr.mask.shape[1]
    lanes = jnp.arange(k, dtype=jnp.int32)
    cities = jnp.arange(n, dtype=jnp.int32)
    _, word_idx, bit, set_bit = bb._mask_consts(n)
    kn = k * n

    # packed layout for v2/v3: [cap, n + W + 4] i32
    # cols: path[0:n] | mask[n:n+W] | depth | cost | bound | sum (bitcast)
    def pack_frontier(f):
        return jnp.concatenate(
            [
                f.path,
                f.mask.astype(jnp.int32),
                f.depth[:, None],
                jax.lax.bitcast_convert_type(f.cost, jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(f.bound, jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(f.sum_min, jnp.int32)[:, None],
            ],
            axis=1,
        )

    packed_variant = comp in (
        "v2_packed_scatter", "v3_gather_dus", "v4_capped_gather_dus"
    )
    packed0 = pack_frontier(fr) if packed_variant else None
    cap_T = min(4 * k, kn)  # v4's block cap

    def stage_once(f, packed, c, capped_ct):
        take = jnp.minimum(f.count, k)
        idx = jnp.maximum(f.count - 1 - lanes, 0)
        live = lanes < take
        if packed_variant:
            # pop FROM the packed carry: the scatter/DUS under test feeds
            # the next iteration's gather, so XLA cannot dead-code it
            # (an earlier harness popped stale f.nodes — the write was a
            # dead carry and DCE-able; flagged in review, re-measured)
            pr = packed[idx]
            p_path = pr[:, :n]
            p_mask = pr[:, n : n + W].astype(jnp.uint32)
            p_depth = pr[:, n + W]
            p_cost = (
                jax.lax.bitcast_convert_type(pr[:, n + W + 1], jnp.float32)
                + c * 0.0
            )
            p_bound = jax.lax.bitcast_convert_type(
                pr[:, n + W + 2], jnp.float32
            )
            p_sum = jax.lax.bitcast_convert_type(
                pr[:, n + W + 3], jnp.float32
            )
        else:
            p_path = f.path[idx]
            p_mask = f.mask[idx]
            p_depth = f.depth[idx]
            p_cost = f.cost[idx] + c * 0.0
            p_bound = f.bound[idx]
            p_sum = f.sum_min[idx]
        if integral:
            live = live & (p_bound <= c - 1.0)
        else:
            live = live & (p_bound < c)
        cur = p_path[lanes, jnp.maximum(p_depth - 1, 0)]
        unvis = (p_mask[:, word_idx] >> bit[None, :]) & 1 == 0
        feasible = unvis & live[:, None]
        ccost = p_cost[:, None] + d32[cur]
        cbound = ccost + p_sum[:, None] + bd.bound_adj[None, :]
        cdepth = p_depth[:, None] + 1
        is_complete = (cdepth == n) & feasible
        total = ccost + d32[cities, 0][None, :]
        comp_total = jnp.where(is_complete, total, bb.INF)
        new_inc = jnp.minimum(c, jnp.min(comp_total))
        if integral:
            push = feasible & ~is_complete & (cbound <= new_inc - 1.0)
        else:
            push = feasible & ~is_complete & (cbound < new_inc)
        child_mask = p_mask[:, None, :] | set_bit[None, :, :]
        child_sum = p_sum[:, None] - bd.min_out[None, :]
        child_path = jnp.broadcast_to(p_path[:, None, :], (k, n, n))
        child_path = jnp.where(
            (jnp.arange(n)[None, None, :]
             == jnp.minimum(p_depth[:, None, None], n - 1)),
            cities[None, :, None],
            child_path,
        )

        keys = jnp.where(push, cbound, -bb.INF)
        child_ord = jnp.argsort(-keys, axis=1)  # [k, n]
        best_child = jnp.min(jnp.where(push, cbound, bb.INF), axis=1)
        parent_key = jnp.where(jnp.isfinite(best_child), best_child, -bb.INF)
        parent_ord = jnp.argsort(-parent_key)  # [k]
        base = f.count - take

        if comp == "v0_order_scatter":
            order = (parent_ord[:, None] * n + child_ord[parent_ord]).reshape(-1)
            flat_push_o = push.reshape(-1)[order]
            n_push = flat_push_o.sum()
            dest = base + jnp.cumsum(flat_push_o.astype(jnp.int32)) - 1
            dest = jnp.where(flat_push_o, dest, f_cap)
            dest = jnp.minimum(dest, f_cap)

            def scat(buf, vals):
                return buf.at[dest].set(vals[order], mode="drop")

            nf = SoAF(
                scat(f.path, child_path.reshape(-1, n)),
                scat(f.mask, child_mask.reshape(-1, W)),
                scat(f.depth, jnp.broadcast_to(cdepth, (k, n)).reshape(-1)),
                scat(f.cost, ccost.reshape(-1)),
                scat(f.bound, cbound.reshape(-1)),
                scat(f.sum_min, child_sum.reshape(-1)),
                jnp.minimum(base + n_push.astype(jnp.int32), f_cap),
                f.overflow | (base + n_push > f_cap),
            )
            return nf, packed, new_inc, capped_ct

        # v1/v2/v3: analytic inverse of the two-level permutation.
        # inv_parent[p] = rank of parent p in parent_ord;
        # inv_child[p, c] = rank of child c within parent p's ordering.
        # priority_pos[p, c] = inv_parent[p] * n + inv_child[p, c]
        # == the position candidate (p, c) holds in v0's `order`.
        inv_parent = jnp.zeros(k, jnp.int32).at[parent_ord].set(
            jnp.arange(k, dtype=jnp.int32)
        )
        inv_child = jnp.zeros((k, n), jnp.int32).at[
            jnp.arange(k, dtype=jnp.int32)[:, None], child_ord
        ].set(jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (k, n)))
        prio = (inv_parent[:, None] * n + inv_child).reshape(-1)  # [kn]
        flat_push = push.reshape(-1)
        # pushed-count prefix over priority order, read back per candidate:
        # flags_in_order[j] = is the j-th-priority candidate pushed?
        flags_in_order = (
            jnp.zeros(kn, jnp.int32).at[prio].set(flat_push.astype(jnp.int32))
        )
        csum = jnp.cumsum(flags_in_order)
        rank = csum[prio] - 1  # rank among pushed, in priority order
        n_push = flat_push.sum()
        dest = jnp.where(flat_push, base + rank, f_cap)
        dest = jnp.minimum(dest, f_cap)

        if comp == "v1_invperm_scatter":
            def scat(buf, vals):
                return buf.at[dest].set(vals, mode="drop")

            nf = SoAF(
                scat(f.path, child_path.reshape(-1, n)),
                scat(f.mask, child_mask.reshape(-1, W)),
                scat(f.depth, jnp.broadcast_to(cdepth, (k, n)).reshape(-1)),
                scat(f.cost, ccost.reshape(-1)),
                scat(f.bound, cbound.reshape(-1)),
                scat(f.sum_min, child_sum.reshape(-1)),
                jnp.minimum(base + n_push.astype(jnp.int32), f_cap),
                f.overflow | (base + n_push > f_cap),
            )
            return nf, packed, new_inc, capped_ct

        # packed candidate rows [kn, n+W+4] i32
        cand = jnp.concatenate(
            [
                child_path.reshape(-1, n),
                child_mask.reshape(-1, W).astype(jnp.int32),
                jnp.broadcast_to(cdepth, (k, n)).reshape(-1)[:, None],
                jax.lax.bitcast_convert_type(ccost.reshape(-1), jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(cbound.reshape(-1), jnp.int32)[:, None],
                jax.lax.bitcast_convert_type(child_sum.reshape(-1), jnp.int32)[:, None],
            ],
            axis=1,
        )
        if comp == "v2_packed_scatter":
            new_packed = packed.at[dest].set(cand, mode="drop")
            cnt = jnp.minimum(base + n_push.astype(jnp.int32), f_cap)
            nf = f._replace(count=cnt)
            return nf, new_packed, new_inc, capped_ct

        # v3/v4: gather packed rows into priority order, then one DUS block.
        # order[j] = index of the j-th-priority candidate (inverse of prio)
        order = jnp.zeros(kn, jnp.int32).at[prio].set(
            jnp.arange(kn, dtype=jnp.int32)
        )
        if comp == "v4_capped_gather_dus":
            # only the first T priority rows are gathered and written —
            # the engine version would lax.cond to the full block when
            # n_push > T; here the count is clamped and the event counted
            block = cand[order[:cap_T]]  # [T, n+W+4]
            start = jnp.minimum(base, f_cap - cap_T)
            new_packed = jax.lax.dynamic_update_slice(packed, block, (start, 0))
            capped = (n_push > cap_T).astype(jnp.int32)
            n_eff = jnp.minimum(n_push.astype(jnp.int32), cap_T)
            cnt = jnp.minimum(base + n_eff, f_cap)
            nf = f._replace(count=cnt)
            return nf, new_packed, new_inc, capped_ct + capped
        block = cand[order]  # [kn, n+W+4] — pushed rows form the prefix
        start = jnp.minimum(base, f_cap - kn)  # stay in bounds (headroom)
        new_packed = jax.lax.dynamic_update_slice(packed, block, (start, 0))
        cnt = jnp.minimum(base + n_push.astype(jnp.int32), f_cap)
        nf = f._replace(count=cnt)
        return nf, new_packed, new_inc, capped_ct

    dummy = (jnp.zeros((1, 1), jnp.int32) if packed0 is None else packed0)
    state0 = soa_fr if comp in ("v0_order_scatter", "v1_invperm_scatter") else fr

    @jax.jit
    def dispatch(carry, capped):
        def body(_, fpc):
            return stage_once(*fpc)

        _, _, c, cap_ct = jax.lax.fori_loop(
            0, args.steps, body, (state0, dummy, carry, capped)
        )
        return c, cap_ct

    t0 = time.perf_counter()
    c, cap_ct = dispatch(inc_cost * 1.0, jnp.asarray(0, jnp.int32))
    jax.block_until_ready(c)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    # cap counter restarts at 0 so capped_events covers exactly the timed
    # dispatches*steps window (the warmup dispatch above is untimed)
    cap_ct = jnp.asarray(0, jnp.int32)
    for _ in range(args.dispatches):
        c, cap_ct = dispatch(c, cap_ct)
    final = float(c)
    capped_events = int(cap_ct)
    wall = time.perf_counter() - t0
    ms = wall * 1000.0 / (args.dispatches * args.steps)
    print(json.dumps({
        "variant": comp,
        "ms_per_step": round(ms, 4),
        "dispatches": args.dispatches,
        "steps_per_dispatch": args.steps,
        "compile_s": round(compile_s, 1),
        "final_value": final,
        "capped_events": capped_events,
        "device": str(dev),
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("instance", nargs="?", default="eil51")
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--warm-steps", type=int, default=10)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dispatches", type=int, default=12)
    ap.add_argument("--out", default="SCATTER_PROFILE.json")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of variants")
    args = ap.parse_args()

    if "TSP_SCATTER_VARIANT" in os.environ:
        return child(args)

    variants = VARIANTS if not args.only else tuple(args.only.split(","))
    results = {}
    for comp in variants:
        env = dict(os.environ, TSP_SCATTER_VARIANT=comp)
        try:
            r = subprocess.run(
                [sys.executable] + sys.argv, capture_output=True,
                text=True, env=env, timeout=1800,
            )
        except subprocess.TimeoutExpired:
            print(f"{comp}: subprocess timed out", file=sys.stderr)
            continue
        sys.stderr.write(r.stderr[-2000:])
        try:
            results[comp] = json.loads(r.stdout.strip().splitlines()[-1])
            print(f"{comp}: {results[comp]['ms_per_step']} ms/step",
                  file=sys.stderr)
        except (json.JSONDecodeError, IndexError):
            print(f"{comp}: no JSON (rc={r.returncode})", file=sys.stderr)
    if not results:
        return 1
    out = {
        "instance": args.instance,
        "k": args.k,
        "method": "chained transfer-free dispatches, one readback per "
        "variant subprocess; no MST chain (push machinery only, "
        "comparable to STEP_PROFILE_FINE scatter=6.87ms)",
        "variants": results,
    }
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    write_json_atomic(args.out, out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())

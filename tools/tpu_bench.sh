#!/usr/bin/env bash
# One-shot TPU benchmark artifact capture (run when the TPU tunnel is up).
#
# Produces:
#   BENCH_TPU_PIPELINE.json - pipeline; bench.py measures BOTH fold shapes
#                             and reports the faster (see its "fold" key)
#   BENCH_BNB_TPU.json      - north-star B&B nodes/sec (eil51, proven)
#   traces/tpu_pipeline/    - jax.profiler trace of the pipeline CLI
#   BENCH_KROA100_TPU.jsonl - kroA100 certified-gap chunked run
#
# Legs are independent (no set -e): the 2026-07-30 capture showed one
# crashed leg (kroA100) aborting the still-unrun trace leg. Legs that
# already produced an artifact in this repo checkout are skipped, so the
# watcher can re-invoke this script after a mid-capture grant lapse and
# only the missing legs run.
set -uo pipefail
cd "$(dirname "$0")/.."

if [ ! -s BENCH_TPU_PIPELINE.json ]; then
    echo "== pipeline (both folds; faster one reported) =="
    python bench.py 2> >(tail -8 >&2) | tee BENCH_TPU_PIPELINE.json
fi

if [ ! -s BENCH_BNB_TPU.json ]; then
    echo "== B&B eil51 (north-star metric) =="
    TSP_BENCH=bnb python bench.py 2> >(tail -3 >&2) | tee BENCH_BNB_TPU.json
fi

if [ "$(wc -l < BENCH_BNB_TPU_KSWEEP.jsonl 2>/dev/null || echo 0)" -lt 2 ]; then
    # completion = both rows present; a partial file (mid-leg crash) must
    # not block the retry, so build in a temp file and move into place
    echo "== B&B eil51 k-sweep (batch-width tuning evidence) =="
    : > BENCH_BNB_TPU_KSWEEP.tmp
    for K in 256 4096; do
        TSP_BENCH=bnb TSP_BENCH_K=$K python bench.py 2> >(tail -2 >&2) \
            | tee -a BENCH_BNB_TPU_KSWEEP.tmp
    done
    [ "$(wc -l < BENCH_BNB_TPU_KSWEEP.tmp)" -ge 2 ] \
        && mv BENCH_BNB_TPU_KSWEEP.tmp BENCH_BNB_TPU_KSWEEP.jsonl
fi

if [ ! -s BENCH_TPU_POLISH.json ]; then
    echo "== pipeline polish fold (measured-length quality headline) =="
    TSP_BENCH_FOLD=tree_xy_polish python bench.py \
        2> >(tail -3 >&2) | tee BENCH_TPU_POLISH.json
    [ -s BENCH_TPU_POLISH.json ] || rm -f BENCH_TPU_POLISH.json
fi

if [ ! -s BENCH_BNB_TPU_BORUVKA.json ]; then
    echo "== B&B eil51, Boruvka MST kernel (log-depth bound vs Prim) =="
    TSP_BENCH=bnb TSP_BENCH_MST_KERNEL=boruvka python bench.py \
        2> >(tail -3 >&2) | tee BENCH_BNB_TPU_BORUVKA.json
    [ -s BENCH_BNB_TPU_BORUVKA.json ] || rm -f BENCH_BNB_TPU_BORUVKA.json
fi

if [ ! -s STEP_PROFILE_TPU.json ]; then
    echo "== B&B step attribution (full vs no-MST vs bound-only) =="
    python tools/step_profile.py eil51 --k=1024 \
        --out=STEP_PROFILE_TPU.json || true
    [ -s STEP_PROFILE_TPU.json ] || rm -f STEP_PROFILE_TPU.json
fi

if [ ! -d traces/tpu_pipeline ]; then
    echo "== profiler trace =="
    rm -rf traces/tpu_pipeline.tmp
    python -m tsp_mpi_reduction_tpu 16 100 1000 1000 --backend=tpu \
        --dtype=float32 --trace traces/tpu_pipeline.tmp | tail -1 \
        && mv traces/tpu_pipeline.tmp traces/tpu_pipeline \
        && echo "trace written to traces/tpu_pipeline"
fi

if [ ! -s BENCH_KROA100_TPU.jsonl ]; then
    echo "== kroA100 chunked (certified-gap evidence on TPU) =="
    # SAFE dispatch sizing: a 20k-step single dispatch (~23 min of XLA
    # execution at the measured ~70 ms/step) crashed the TPU worker on
    # 2026-07-30; probes up to ~12 s executed fine. 300 steps ~= 21 s
    # per dispatch; each chunk is one dispatch (fresh process, cached
    # compile), so the run is many short executions instead of one
    # unbounded one.
    rm -f /tmp/kroa_tpu_ck.npz
    python tools/bnb_chunked.py kroA100 --chunk-iters=300 --max-chunks=40 --mst-kernel=prim_pallas \
        --time-limit=420 --chunk-timeout=240 --checkpoint=/tmp/kroa_tpu_ck \
        --k=1024 --capacity=$((1<<19)) | tee BENCH_KROA100_TPU.tmp
    # completion = the driver's final summary line made it out; a partial
    # chunk log must not block the watcher's next retry
    grep -q '"chunks"' BENCH_KROA100_TPU.tmp \
        && mv BENCH_KROA100_TPU.tmp BENCH_KROA100_TPU.jsonl
fi

#!/usr/bin/env bash
# One-shot TPU benchmark artifact capture (run when the TPU tunnel is up).
#
# Produces:
#   BENCH_TPU_PIPELINE.json      - pipeline, tree fold (bench.py default)
#   BENCH_TPU_PIPELINE_SCAN.json - pipeline, r01/r02 sequential fold
#   BENCH_BNB_TPU.json           - north-star B&B nodes/sec (eil51, proven)
#   traces/tpu_pipeline/         - jax.profiler trace of the pipeline CLI
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== pipeline (tree fold) =="
python bench.py 2> >(tail -5 >&2) | tee BENCH_TPU_PIPELINE.json

echo "== pipeline (scan fold, r01/r02 method) =="
TSP_BENCH_FOLD=scan python bench.py 2> >(tail -3 >&2) | tee BENCH_TPU_PIPELINE_SCAN.json

echo "== B&B eil51 (north-star metric) =="
TSP_BENCH=bnb python bench.py 2> >(tail -3 >&2) | tee BENCH_BNB_TPU.json

echo "== profiler trace =="
python -m tsp_mpi_reduction_tpu 16 100 1000 1000 --backend=tpu \
    --dtype=float32 --trace traces/tpu_pipeline | tail -1
echo "trace written to traces/tpu_pipeline"

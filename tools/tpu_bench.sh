#!/usr/bin/env bash
# One-shot TPU benchmark artifact capture (run when the TPU tunnel is up).
#
# Produces:
#   BENCH_TPU_PIPELINE.json - pipeline; bench.py measures BOTH fold shapes
#                             and reports the faster (see its "fold" key)
#   BENCH_BNB_TPU.json      - north-star B&B nodes/sec (eil51, proven)
#   traces/tpu_pipeline/    - jax.profiler trace of the pipeline CLI
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== pipeline (both folds; faster one reported) =="
python bench.py 2> >(tail -8 >&2) | tee BENCH_TPU_PIPELINE.json

echo "== B&B eil51 (north-star metric) =="
TSP_BENCH=bnb python bench.py 2> >(tail -3 >&2) | tee BENCH_BNB_TPU.json

echo "== B&B eil51 k-sweep (batch-width tuning evidence) =="
: > BENCH_BNB_TPU_KSWEEP.jsonl
for K in 256 4096; do
    TSP_BENCH=bnb TSP_BENCH_K=$K python bench.py 2> >(tail -2 >&2) \
        | tee -a BENCH_BNB_TPU_KSWEEP.jsonl
done

echo "== kroA100 chunked (certified-gap evidence on TPU) =="
rm -f /tmp/kroa_tpu_ck.npz
python tools/bnb_chunked.py kroA100 --chunk-iters=20000 --max-chunks=3 \
    --time-limit=420 --chunk-timeout=900 --checkpoint=/tmp/kroa_tpu_ck \
    --k=1024 --capacity=$((1<<19)) | tee BENCH_KROA100_TPU.jsonl

echo "== profiler trace =="
python -m tsp_mpi_reduction_tpu 16 100 1000 1000 --backend=tpu \
    --dtype=float32 --trace traces/tpu_pipeline | tail -1
echo "trace written to traces/tpu_pipeline"

#!/usr/bin/env bash
# One-shot TPU benchmark artifact capture (run when the TPU tunnel is up).
#
# Produces:
#   BENCH_TPU_PIPELINE.json - pipeline; bench.py measures BOTH fold shapes
#                             and reports the faster (see its "fold" key)
#   BENCH_BNB_TPU.json      - north-star B&B nodes/sec (eil51, proven)
#   traces/tpu_pipeline/    - jax.profiler trace of the pipeline CLI
#   BENCH_KROA100_TPU.jsonl - kroA100 certified-gap chunked run
#
# Legs are independent (no set -e): the 2026-07-30 capture showed one
# crashed leg (kroA100) aborting the still-unrun trace leg. Legs that
# already produced an artifact in this repo checkout are skipped, so the
# watcher can re-invoke this script after a mid-capture grant lapse and
# only the missing legs run.
set -uo pipefail
cd "$(dirname "$0")/.."

# ---------------- round-5 legs (fresh engine recaptures) ----------------
# VERDICT r4: refresh the step attribution on the FINAL packed+Pallas
# engine (the committed STEP_PROFILE_*TPU.json profile the r3 SoA step),
# recapture the north-star bench + k-sweep on it, run the kroA100 LB climb
# to exhaustion, and demonstrate the sweep protocol on-chip. Safe legs
# first; the n>128 bisection is LAST (an n=200 dispatch can crash the TPU
# worker and forfeit the whole grant — claim log 2026-07-31 08:30Z).

if [ ! -s STEP_PROFILE_R5_TPU.json ]; then
    echo "== r5 step attribution (final engine, Pallas Prim) =="
    python tools/step_profile.py eil51 --k=1024 --mst-kernel=prim_pallas \
        --only=full_prim,nomst,bound_prim,guarded \
        --out=STEP_PROFILE_R5_TPU.json || true
    [ -s STEP_PROFILE_R5_TPU.json ] || rm -f STEP_PROFILE_R5_TPU.json
fi

if [ ! -s STEP_PROFILE_FINE_R5_TPU.json ]; then
    echo "== r5 fine step attribution (popgather/sort/scatter, packed) =="
    python tools/step_profile.py eil51 --k=1024 --fine \
        --out=STEP_PROFILE_FINE_R5_TPU.json || true
    [ -s STEP_PROFILE_FINE_R5_TPU.json ] || rm -f STEP_PROFILE_FINE_R5_TPU.json
fi

if [ ! -s BENCH_STEP_FUSED_TPU.json ]; then
    echo "== r6 fused-vs-reference expansion step (ISSUE 8, compiled Pallas) =="
    TSP_BENCH=step TSP_BENCH_STEP_OUT=BENCH_STEP_FUSED_TPU.json \
        TSP_BENCH_HISTORY=off python bench.py 2> >(tail -3 >&2) || true
    [ -s BENCH_STEP_FUSED_TPU.json ] || rm -f BENCH_STEP_FUSED_TPU.json
    # TPU captures join the same bench history as CPU runs (ISSUE 9):
    # one fingerprinted record through the shared locked appender
    [ -s BENCH_STEP_FUSED_TPU.json ] && python tools/bench_check.py \
        append BENCH_STEP_FUSED_TPU.json --mode step --backend tpu || true
fi

if [ ! -s BENCH_BALANCE_TPU.json ]; then
    echo "== adaptive load balance A/B (ISSUE 15; CPU virtual mesh — the"
    echo "   controller/collective logic is backend-agnostic, the leg runs"
    echo "   here so the TPU capture set carries the same artifact) =="
    TSP_BENCH=balance TSP_BENCH_BALANCE_OUT=BENCH_BALANCE_TPU.json \
        TSP_BENCH_HISTORY=off python bench.py 2> >(tail -3 >&2) | tail -1
    [ -s BENCH_BALANCE_TPU.json ] || rm -f BENCH_BALANCE_TPU.json
    [ -s BENCH_BALANCE_TPU.json ] && python tools/bench_check.py \
        append BENCH_BALANCE_TPU.json --mode balance --backend tpu || true
fi

if [ ! -s BENCH_BNB_TPU_R5.json ]; then
    echo "== r5 B&B eil51 recapture (north-star metric, final engine) =="
    TSP_BENCH=bnb TSP_BENCH_HISTORY=off python bench.py 2> >(tail -3 >&2) | tee BENCH_BNB_TPU_R5.json
    [ -s BENCH_BNB_TPU_R5.json ] || rm -f BENCH_BNB_TPU_R5.json
    [ -s BENCH_BNB_TPU_R5.json ] && python tools/bench_check.py \
        append BENCH_BNB_TPU_R5.json --mode bnb --backend tpu || true
fi

if [ ! -s BENCH_BNB_TPU_R5_NOSORT.json ]; then
    echo "== r5 B&B eil51, natural push order (sort-free step A/B) =="
    TSP_BENCH=bnb TSP_BENCH_PUSH_ORDER=natural TSP_BENCH_HISTORY=off python bench.py \
        2> >(tail -3 >&2) | tee BENCH_BNB_TPU_R5_NOSORT.json
    [ -s BENCH_BNB_TPU_R5_NOSORT.json ] || rm -f BENCH_BNB_TPU_R5_NOSORT.json
fi

if [ ! -s BENCH_BNB_TPU_R5_CAPPED.json ]; then
    echo "== r5 B&B eil51, capped push block (scatter v4, engine A/B) =="
    TSP_BENCH=bnb TSP_BENCH_PUSH_BLOCK=4096 TSP_BENCH_HISTORY=off python bench.py \
        2> >(tail -3 >&2) | tee BENCH_BNB_TPU_R5_CAPPED.json
    [ -s BENCH_BNB_TPU_R5_CAPPED.json ] || rm -f BENCH_BNB_TPU_R5_CAPPED.json
fi

if [ ! -s BENCH_BNB_TPU_R5_COMBO.json ]; then
    # best-guess combined config: k=256 won the r4 k-sweep (199k vs
    # 172.5k at k=1024) and the capped block is the biggest single-step
    # saving candidate. The cap scales with k (T = 4*k rows: 1024 here,
    # mirroring the CAPPED leg's 4096 at k=1024 and scatter_profile's
    # cap_T = min(4k, kn)), so the combo differs from CAPPED in k only
    # modulo that scaling; the pure k effect is isolated by the KSWEEP
    # leg and the pure cap effect by CAPPED vs the plain R5 leg.
    # Captured so an unattended grant records the likely-best config
    # even before any interactive tuning session.
    echo "== r5 B&B eil51, combo (k=256 + capped push block) =="
    TSP_BENCH=bnb TSP_BENCH_K=256 TSP_BENCH_PUSH_BLOCK=1024 TSP_BENCH_HISTORY=off python bench.py \
        2> >(tail -3 >&2) | tee BENCH_BNB_TPU_R5_COMBO.json
    [ -s BENCH_BNB_TPU_R5_COMBO.json ] || rm -f BENCH_BNB_TPU_R5_COMBO.json
fi

if [ "$(wc -l < BENCH_BNB_TPU_KSWEEP_R5.jsonl 2>/dev/null || echo 0)" -lt 4 ]; then
    echo "== r5 B&B eil51 k-sweep =="
    : > BENCH_BNB_TPU_KSWEEP_R5.tmp
    for K in 128 256 512 2048; do
        TSP_BENCH=bnb TSP_BENCH_K=$K TSP_BENCH_HISTORY=off python bench.py 2> >(tail -2 >&2) \
            | tee -a BENCH_BNB_TPU_KSWEEP_R5.tmp
    done
    [ "$(wc -l < BENCH_BNB_TPU_KSWEEP_R5.tmp)" -ge 4 ] \
        && mv BENCH_BNB_TPU_KSWEEP_R5.tmp BENCH_BNB_TPU_KSWEEP_R5.jsonl
fi

if [ ! -s results_tpu.csv ]; then
    # the reference's own protocol (test.sh) on-chip: all cities x all
    # blocks at procs=8 (the north-star rank count; 1200 full configs =
    # 1200 XLA compiles through the relay — stated subset instead). Two
    # passes: the first populates the persistent compile cache, the
    # second measures warm (reference has no JIT; compile is one-time).
    echo "== r5 TPU sweep (reference protocol, stated subset) =="
    python tools/sweep.py --backend=tpu --procs=8 \
        --out=results_tpu_coldpass.csv --force \
        && python tools/sweep.py --backend=tpu --procs=8 \
            --out=results_tpu.csv --force
    [ -s results_tpu.csv ] || rm -f results_tpu.csv
fi

if [ ! -s BENCH_KROA100_R5_EXHAUST.jsonl ]; then
    echo "== r5 kroA100 LB climb to exhaustion (stop: <0.5/chunk over 5) =="
    rm -f /tmp/kroa_r5_ck.npz
    python tools/bnb_chunked.py kroA100 --chunk-iters=300 --max-chunks=200 \
        --mst-kernel=prim_pallas --time-limit=10800 --chunk-timeout=300 \
        --checkpoint=/tmp/kroa_r5_ck --k=1024 --capacity=$((1<<19)) \
        --node-ascent=6 --reorder-every=16 \
        --lb-stall-gain=0.5 --lb-stall-chunks=5 | tee BENCH_KROA100_R5_EXHAUST.tmp
    grep -q '"chunks"' BENCH_KROA100_R5_EXHAUST.tmp \
        && mv BENCH_KROA100_R5_EXHAUST.tmp BENCH_KROA100_R5_EXHAUST.jsonl
fi

if [ ! -s NMAX_BISECT_TPU.jsonl ]; then
    # LAST: bisect the n>128 worker-crash boundary (BASELINE configs[5]
    # random200). Each probe is a tiny short dispatch in its own process;
    # a crash here can forfeit the grant, hence the terminal position.
    echo "== r5 n-boundary bisection (crash risk: sequenced last) =="
    : > NMAX_BISECT_TPU.tmp
    for N in 136 152 168 184 200; do
        echo "-- random:$N probe --"
        timeout 600 python tools/bnb_solve.py "random:$N" --backend=tpu \
            --k=64 --max-iters=128 --inner-steps=16 --device-loop=on \
            --capacity=$((1<<17)) --node-ascent=0 > nmax_probe.out 2> nmax_probe.err
        rc=$?
        # JSON row built in python: shell quoting cannot safely embed an
        # arbitrary stderr tail (backslashes, control chars) or a
        # timeout-truncated stdout fragment
        python - "$N" "$rc" >> NMAX_BISECT_TPU.tmp <<'PYEOF'
import json, sys
n, rc = int(sys.argv[1]), int(sys.argv[2])
try:
    lines = open("nmax_probe.out", errors="replace").read().strip().splitlines()
except OSError:
    lines = []
err = ""
try:
    err = open("nmax_probe.err", errors="replace").read()[-300:]
except OSError:
    pass
result = None
if lines:
    try:
        result = json.loads(lines[-1])
    except ValueError:
        pass
print(json.dumps({"n": n, "rc": rc, "ok": result is not None,
                  "err": err, "result": result}))
PYEOF
    done
    mv NMAX_BISECT_TPU.tmp NMAX_BISECT_TPU.jsonl
fi

# ---------------- round-4 legs (artifact-gated; normally all skip) -------

if [ ! -s BENCH_TPU_PIPELINE.json ]; then
    echo "== pipeline (both folds; faster one reported) =="
    TSP_BENCH_HISTORY=off python bench.py 2> >(tail -8 >&2) | tee BENCH_TPU_PIPELINE.json
    [ -s BENCH_TPU_PIPELINE.json ] && python tools/bench_check.py \
        append BENCH_TPU_PIPELINE.json --mode pipeline --backend tpu || true
fi

if [ ! -s BENCH_BNB_TPU.json ]; then
    echo "== B&B eil51 (north-star metric) =="
    TSP_BENCH=bnb TSP_BENCH_HISTORY=off python bench.py 2> >(tail -3 >&2) | tee BENCH_BNB_TPU.json
fi

if [ "$(wc -l < BENCH_BNB_TPU_KSWEEP.jsonl 2>/dev/null || echo 0)" -lt 2 ]; then
    # completion = both rows present; a partial file (mid-leg crash) must
    # not block the retry, so build in a temp file and move into place
    echo "== B&B eil51 k-sweep (batch-width tuning evidence) =="
    : > BENCH_BNB_TPU_KSWEEP.tmp
    for K in 256 4096; do
        TSP_BENCH=bnb TSP_BENCH_K=$K TSP_BENCH_HISTORY=off python bench.py 2> >(tail -2 >&2) \
            | tee -a BENCH_BNB_TPU_KSWEEP.tmp
    done
    [ "$(wc -l < BENCH_BNB_TPU_KSWEEP.tmp)" -ge 2 ] \
        && mv BENCH_BNB_TPU_KSWEEP.tmp BENCH_BNB_TPU_KSWEEP.jsonl
fi

if [ ! -s BENCH_TPU_POLISH.json ]; then
    echo "== pipeline polish fold (measured-length quality headline) =="
    TSP_BENCH_FOLD=tree_xy_polish TSP_BENCH_HISTORY=off python bench.py \
        2> >(tail -3 >&2) | tee BENCH_TPU_POLISH.json
    [ -s BENCH_TPU_POLISH.json ] || rm -f BENCH_TPU_POLISH.json
fi

if [ ! -s BENCH_BNB_TPU_BORUVKA.json ]; then
    echo "== B&B eil51, Boruvka MST kernel (log-depth bound vs Prim) =="
    TSP_BENCH=bnb TSP_BENCH_MST_KERNEL=boruvka TSP_BENCH_HISTORY=off python bench.py \
        2> >(tail -3 >&2) | tee BENCH_BNB_TPU_BORUVKA.json
    [ -s BENCH_BNB_TPU_BORUVKA.json ] || rm -f BENCH_BNB_TPU_BORUVKA.json
fi

if [ ! -s STEP_PROFILE_TPU.json ]; then
    echo "== B&B step attribution (full vs no-MST vs bound-only) =="
    python tools/step_profile.py eil51 --k=1024 \
        --out=STEP_PROFILE_TPU.json || true
    [ -s STEP_PROFILE_TPU.json ] || rm -f STEP_PROFILE_TPU.json
fi

if [ ! -d traces/tpu_pipeline ]; then
    echo "== profiler trace =="
    rm -rf traces/tpu_pipeline.tmp
    python -m tsp_mpi_reduction_tpu 16 100 1000 1000 --backend=tpu \
        --dtype=float32 --trace traces/tpu_pipeline.tmp | tail -1 \
        && mv traces/tpu_pipeline.tmp traces/tpu_pipeline \
        && echo "trace written to traces/tpu_pipeline"
fi

if [ ! -s BENCH_KROA100_TPU.jsonl ]; then
    echo "== kroA100 chunked (certified-gap evidence on TPU) =="
    # SAFE dispatch sizing: a 20k-step single dispatch (~23 min of XLA
    # execution at the measured ~70 ms/step) crashed the TPU worker on
    # 2026-07-30; probes up to ~12 s executed fine. 300 steps ~= 21 s
    # per dispatch; each chunk is one dispatch (fresh process, cached
    # compile), so the run is many short executions instead of one
    # unbounded one.
    rm -f /tmp/kroa_tpu_ck.npz
    python tools/bnb_chunked.py kroA100 --chunk-iters=300 --max-chunks=40 --mst-kernel=prim_pallas \
        --time-limit=420 --chunk-timeout=240 --checkpoint=/tmp/kroa_tpu_ck \
        --k=1024 --capacity=$((1<<19)) | tee BENCH_KROA100_TPU.tmp
    # completion = the driver's final summary line made it out; a partial
    # chunk log must not block the watcher's next retry
    grep -q '"chunks"' BENCH_KROA100_TPU.tmp \
        && mv BENCH_KROA100_TPU.tmp BENCH_KROA100_TPU.jsonl
fi

if [ ! -s BENCH_COMPILE_CACHE_TPU.json ]; then
    echo "== compile-once: cold vs warm chunk startup + serve first flush =="
    # PR 5 leg: captures the 50-110 s/component TPU compile savings
    # (STEP_PROFILE_FINE_TPU.json) as a measured cold/warm ratio. The
    # parent spawns fresh child processes per measurement; each child
    # claims the chip in turn (same discipline as the chunked driver).
    TSP_BENCH=compile TSP_BENCH_COMPILE_OUT=BENCH_COMPILE_CACHE_TPU.json \
        TSP_BENCH_HISTORY=off python bench.py 2> >(tail -3 >&2) | tail -1
    [ -s BENCH_COMPILE_CACHE_TPU.json ] || rm -f BENCH_COMPILE_CACHE_TPU.json
    [ -s BENCH_COMPILE_CACHE_TPU.json ] && python tools/bench_check.py \
        append BENCH_COMPILE_CACHE_TPU.json --mode compile --backend tpu || true
fi

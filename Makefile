# Build & benchmark harness — the reference's L0 layer re-hosted
# (Makefile:1-21, test.sh:1-24 in /root/reference; SURVEY.md §1 row L0).
#
# The reference's `make` builds the MPI binary and `make run` launches
# `mpirun -np 3 ./tsp 10 6 500 500` (Makefile:20). Here `make` builds the
# native C++ runtime and the bit-exact CPU oracle (the unmodified reference
# translation unit compiled out-of-tree against our single-rank MPI stub —
# no reference code is vendored into this repo), and `make run` drives the
# TPU-native CLI with the same config and a 3-rank-shaped merge tree.

REFERENCE ?= /root/reference
ORACLE_OUT ?= build/oracle
PY ?= python
CXX ?= g++
CXXFLAGS ?= -O2 -std=c++11

.PHONY: all lint chaos native oracle test test-fast bench bench-serve bench-faults bench-compile bench-obs bench-step bench-shard bench-balance bench-fleet bench-check run sweep goldens clean

all: lint native oracle chaos bench-check

# --- static analysis: one gate, two passes against ONE shared baseline —
# graftlint (syntactic AST rules R1-R8 + R13) + graftflow (interprocedural
# dataflow rules R9-R12: lock-discipline races, use-after-donate,
# static-arg recompile risk, shard_map axis-name drift; see README). The
# CLI runs both and FAILS on new findings of either pass and on dead
# baseline scopes for any rule. Plus ruff when available (ruff.toml pins
# a minimal critical-error set; the container image has no ruff, so fall
# back to a syntax-only compile check). The default target set covers the
# whole package — including the serve/ layer, which the zero-entry
# baseline ratchet holds to no hot-path debt. `--sarif out.sarif` /
# tools/lint_report.py produce the CI-facing artifacts.
lint:
	$(PY) -m tsp_mpi_reduction_tpu.analysis
	@if command -v ruff >/dev/null 2>&1; then ruff check .; \
	else echo "lint: ruff not installed — syntax-only compile check instead"; \
	$(PY) -m compileall -q tsp_mpi_reduction_tpu tools tests bench.py; fi

# --- chaos suite: one injected fault per run at every resilience seam
# (tests/test_chaos.py; the TSP_FAULTS registry, README "Fault tolerance").
# Chained into the default target: a seam without working recovery fails
# the build, not the incident.
chaos:
	$(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

# --- native C++ runtime (generator, Held-Karp, merge, pipeline) ---
native:
	$(MAKE) -C native

# --- bit-exact oracle: reference tsp.cpp + golden dumper ---
oracle: $(ORACLE_OUT)/tsp $(ORACLE_OUT)/dump

$(ORACLE_OUT)/tsp: $(REFERENCE)/tsp.cpp $(REFERENCE)/assignment2.h oracle/mpi.h
	@mkdir -p $(ORACLE_OUT)
	$(CXX) $(CXXFLAGS) -Ioracle -I$(REFERENCE) $(REFERENCE)/tsp.cpp -o $@ -lm

$(ORACLE_OUT)/dump: oracle/dump.cpp $(REFERENCE)/tsp.cpp $(REFERENCE)/assignment2.h oracle/mpi.h
	@mkdir -p $(ORACLE_OUT)
	$(CXX) $(CXXFLAGS) -Ioracle -I$(REFERENCE) -Dmain=tsp_reference_main \
		-c $(REFERENCE)/tsp.cpp -o $(ORACLE_OUT)/tspref.o
	$(CXX) $(CXXFLAGS) -Ioracle -I$(REFERENCE) oracle/dump.cpp \
		$(ORACLE_OUT)/tspref.o -o $@ -lm

# --- tests (CPU, 8 virtual devices; tests/conftest.py pins the platform) ---
test:
	$(PY) -m pytest tests/ -x -q

test-fast:
	$(PY) -m pytest tests/ -x -q -m "not slow"

# --- benchmark: one JSON line on the current accelerator ---
bench:
	$(PY) bench.py

# serving-layer acceptance bench: batched vs sequential throughput, the
# mixed-workload continuous-batching ratio (head-of-line B&B proof
# preempted into slices vs run to completion), tight-deadline tier
# routing, cache hit rate -> BENCH_SERVE.json. Chains the history gate
# so the two governed serve series (serve_service_ratio,
# serve_tight_deadline_exact_rate) are judged in the same make target.
bench-serve:
	TSP_BENCH=serve $(PY) bench.py
	$(MAKE) bench-check

# atomic-checkpoint overhead vs the legacy direct write -> BENCH_FAULTS.json
bench-faults:
	TSP_BENCH=faults $(PY) bench.py

# compile-once acceptance bench: cold vs warm chunk-process startup and
# serve first-flush latency (fresh subprocesses against one shared
# TSP_COMPILE_CACHE dir) -> BENCH_COMPILE_CACHE.json
bench-compile:
	TSP_BENCH=compile $(PY) bench.py

# fused-vs-reference expansion-step bench (ISSUE 8): per-step ms +
# nodes/s per kernel in fresh subprocesses, packed-row bytes ratio
# -> BENCH_STEP_FUSED.json
bench-step:
	TSP_BENCH=step $(PY) bench.py

# telemetry acceptance bench: full obs (metrics+tracing+sampler) vs
# TSP_OBS=off B&B wall overhead (<= 2%) + serve span-tree completeness
# -> BENCH_OBS.json
bench-obs:
	TSP_BENCH=obs $(PY) bench.py

# rank-resolved telemetry bench (ISSUE 10): metered per-dispatch rank-hook
# cost (<= 2%, serial-hook estimator) on a deliberately skewed 4-rank CPU
# mesh + per-rank accounting coherence + starved-rank naming
# -> BENCH_SHARD_OBS.json
bench-shard:
	TSP_BENCH=shard $(PY) bench.py

# adaptive load-balance bench (ISSUE 15): static ring vs adaptive
# controller on the skewed 4-rank config (>= 5x imbalance reduction at
# equal-or-better wall, same proven optimum), plus the balanced-mesh
# zero-dispatch control -> BENCH_BALANCE.json; chained into bench-check
# via the governed shard_balance_imbalance / shard_steal_bytes_per_node
# series
bench-balance:
	TSP_BENCH=balance $(PY) bench.py
	$(MAKE) bench-check

# fleet serving bench (ISSUE 11): sustained RPS + p99 vs replica count
# 1/2/4 (clean, then under injected replica.kill), plus the chaos
# acceptance demo — 3 replicas x 48 mixed-deadline requests through
# kills AND hangs: 100% answered exactly once with valid tours,
# cross-replica shared-cache hits, restarts/redispatches in health,
# stitched traces with zero orphans -> BENCH_FLEET.json. The governed
# history metric is the answered-exactly-once rate (counter estimator).
bench-fleet:
	TSP_BENCH=fleet $(PY) bench.py

# regression sentinel over bench_history.jsonl (ISSUE 9): every TSP_BENCH
# run appends a fingerprinted record; this gate fails when a governed
# metric's newest sample is worse than its history allows (median + MAD
# model, per-metric direction/threshold — obs/bench_history.py). Chained
# into the default target; tolerant below min-samples, so a fresh clone
# passes while the history accretes.
bench-check:
	$(PY) tools/bench_check.py

# reference `make run` analog: same config, 3-rank-shaped merge tree
run:
	$(PY) -m tsp_mpi_reduction_tpu 10 6 500 500 --ranks=3

# reference test.sh analog (full 1200-config sweep; see ./test.sh)
sweep:
	./test.sh

# regenerate every golden fixture from the oracle (config parsed from the
# fixture filename full_{ncpb}x{nblocks}_{gx}x{gy}.json)
goldens: oracle
	$(ORACLE_OUT)/dump rand 0 0 0 0 goldens/glibc_rand_seed0.json
	@for f in goldens/full_*.json; do \
		cfg=$$(basename $$f .json | sed 's/full_//; s/[x_]/ /g'); \
		echo "dump full $$cfg -> $$f"; \
		$(ORACLE_OUT)/dump full $$cfg $$f; \
	done

clean:
	rm -rf build
	$(MAKE) -C native clean

// Single-rank MPI stub for compiling the reference without an MPI toolchain.
#ifndef MPI_STUB_H
#define MPI_STUB_H
#include <cstdlib>
#include <cstdio>
#include <cstddef>
#include <map>   // reference relies on mpi.h transitively providing <map>

typedef int MPI_Comm;
typedef int MPI_Datatype;
typedef long MPI_Aint;
typedef int MPI_Request;
typedef struct { int MPI_SOURCE, MPI_TAG, MPI_ERROR; } MPI_Status;

#define MPI_COMM_WORLD 0
#define MPI_INT 1
#define MPI_DOUBLE 2

static inline int MPI_Init(int*, char***) { return 0; }
static inline int MPI_Finalize() { return 0; }
static inline int MPI_Comm_rank(MPI_Comm, int* r) { *r = 0; return 0; }
static inline int MPI_Comm_size(MPI_Comm, int* s) { *s = 1; return 0; }
static inline int MPI_Type_create_struct(int, const int*, const MPI_Aint*,
                                         const MPI_Datatype*, MPI_Datatype* t) { *t = 99; return 0; }
static inline int MPI_Type_commit(MPI_Datatype*) { return 0; }
static inline int MPI_Cart_create(MPI_Comm, int, const int*, const int*, int, MPI_Comm* c) { *c = 1; return 0; }
static inline int MPI_Cart_coords(MPI_Comm, int, int, int* coords) { coords[0] = 0; coords[1] = 0; return 0; }
static inline int MPI_Barrier(MPI_Comm) { return 0; }
static inline int MPI_Send(const void*, int, MPI_Datatype, int, int, MPI_Comm) {
    fprintf(stderr, "stub MPI_Send called at size 1\n"); abort();
}
static inline int MPI_Recv(void*, int, MPI_Datatype, int, int, MPI_Comm, MPI_Status*) {
    fprintf(stderr, "stub MPI_Recv called at size 1\n"); abort();
}
#endif

// Golden-data dumper: links the UNMODIFIED reference translation unit
// (tsp.cpp compiled with -Dmain=tsp_reference_main) and records its exact
// behavior as JSON.
#define fRand dump_fRand
#define printMatrix dump_printMatrix
#define printBlocked dump_printBlocked
#define printMatrixArray dump_printMatrixArray
#define genKey dump_genKey
#define computeDistanceMatrix dump_computeDistanceMatrix
#define printPath dump_printPath
#define convPathToCityPath dump_convPathToCityPath
#define generateSubsets dump_generateSubsets
#include "assignment2.h"
#undef fRand
#undef printMatrix
#undef printBlocked
#undef printMatrixArray
#undef genKey
#undef computeDistanceMatrix
#undef printPath
#undef convPathToCityPath
#undef generateSubsets

extern int procNum;
extern int numProcs;
vector<int> getBlocksPerDim(int numBlocks);

static void printCity(FILE* f, const City& c, bool last) {
    fprintf(f, "[%d,%.17g,%.17g]%s", c.id, c.x, c.y, last ? "" : ",");
}

static void dumpSolution(FILE* f, const BlockSolution& s) {
    fprintf(f, "{\"cost\":%.17g,\"ids\":[", s.cost);
    for (size_t i = 0; i < s.path.size(); i++)
        fprintf(f, "%d%s", s.path[i].id, i + 1 == s.path.size() ? "" : ",");
    fprintf(f, "]}");
}

int main(int argc, char** argv) {
    if (argc != 7) { fprintf(stderr, "usage: dump mode ncpb nblocks gx gy out.json\n"); return 1; }
    const char* mode = argv[1];
    int ncpb = atoi(argv[2]), nblocks = atoi(argv[3]), gx = atoi(argv[4]), gy = atoi(argv[5]);
    FILE* f = fopen(argv[6], "w");
    procNum = 0; numProcs = 1;
    srand(0);

    if (string(mode) == "rand") {
        fprintf(f, "{\"seed\":0,\"values\":[");
        for (int i = 0; i < 2000; i++) fprintf(f, "%d%s", rand(), i == 1999 ? "" : ",");
        fprintf(f, "]}\n");
        fclose(f); return 0;
    }

    vector<int> dims = getBlocksPerDim(nblocks);
    vector<vector<City>> blocks = distributeCities(ncpb, dims[0], dims[1], gx, gy);

    fprintf(f, "{\"config\":{\"ncpb\":%d,\"nblocks\":%d,\"gx\":%d,\"gy\":%d},", ncpb, nblocks, gx, gy);
    fprintf(f, "\"dims\":[%d,%d],", dims[0], dims[1]);
    fprintf(f, "\"blocks\":[");
    for (size_t b = 0; b < blocks.size(); b++) {
        fprintf(f, "[");
        for (size_t j = 0; j < blocks[b].size(); j++) printCity(f, blocks[b][j], j + 1 == blocks[b].size());
        fprintf(f, "]%s", b + 1 == blocks.size() ? "" : ",");
    }
    fprintf(f, "]");

    if (string(mode) == "full") {
        vector<BlockSolution> sols;
        for (size_t b = 0; b < blocks.size(); b++) sols.push_back(tsp(blocks[b]));
        fprintf(f, ",\"block_solutions\":[");
        for (size_t b = 0; b < sols.size(); b++) {
            dumpSolution(f, sols[b]);
            fprintf(f, "%s", b + 1 == sols.size() ? "" : ",");
        }
        fprintf(f, "],\"fold_costs\":[");
        bool first = true;
        while (sols.size() > 1) {
            sols[0] = mergeBlocks(sols[0], sols[1]);
            sols.erase(sols.begin() + 1);
            fprintf(f, "%s%.17g", first ? "" : ",", sols[0].cost);
            first = false;
        }
        fprintf(f, "],\"final\":");
        dumpSolution(f, sols[0]);
    }
    fprintf(f, "}\n");
    fclose(f);
    return 0;
}

#!/bin/bash
# Benchmark sweep — the reference's test.sh re-hosted (test.sh:1-24 in
# /root/reference; SURVEY.md §3.5). Same axes (cities/block 5-10, blocks
# 10..200 step 10, "procs" 2..20 step 2 served by the rank-emulated merge
# tree), same 1000x1000 grid, same results.csv schema
# `numCities,numBlocks,numProcs,time,cost`.
#
# Usage:
#   ./test.sh                 # full 1200-config sweep (slow)
#   ./test.sh --quick         # small smoke subset
#   ./test.sh --backend=cpu   # any tools/sweep.py flag passes through
set -euo pipefail
cd "$(dirname "$0")"
exec python tools/sweep.py "$@"

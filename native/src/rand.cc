/* Bit-exact replica of glibc's default rand() (TYPE_3 additive feedback).
 *
 * The reference's whole instance is a deterministic function of srand(0)
 * plus a strictly ordered rand() sequence (tsp.cpp:273, assignment2.h:86-91),
 * so this replica is the determinism root shared by the native pipeline and
 * the Python generator (ops/rand.py implements the identical algorithm; the
 * two are cross-checked in tests/test_native.py).
 *
 * Algorithm (public, documented in glibc stdlib/random_r.c): a 31-word
 * additive-feedback generator with taps at lags 3 and 31, Lehmer-seeded,
 * first 310 outputs discarded, each output is the new word >> 1.
 */
#include "tsp_native.h"

void tsp_srand(tsp_rand_t* g, uint32_t seed) {
  if (seed == 0) seed = 1;
  uint32_t r[344];
  r[0] = seed;
  /* Lehmer seeding runs on int32 words with C truncating division. */
  int64_t word = (int32_t)seed;
  for (int i = 1; i < 31; i++) {
    int64_t hi = word / 127773;
    int64_t lo = word % 127773;
    word = 16807 * lo - 2836 * hi;
    if (word < 0) word += 2147483647;
    r[i] = (uint32_t)word;
  }
  for (int i = 31; i < 34; i++) r[i] = r[i - 31];
  for (int i = 34; i < 344; i++) r[i] = r[i - 31] + r[i - 3]; /* mod 2^32 */
  /* keep the last 31 words; r[313] is the oldest (lag-31 tap of output 0) */
  for (int i = 0; i < 31; i++) g->window[i] = r[313 + i];
  g->pos = 0;
}

int32_t tsp_rand_next(tsp_rand_t* g) {
  int p = g->pos;
  uint32_t val = g->window[p] + g->window[(p + 28) % 31]; /* lags 31 and 3 */
  g->window[p] = val; /* oldest slot becomes the newest word */
  g->pos = (p + 1) % 31;
  return (int32_t)(val >> 1);
}

void tsp_rand_stream(uint32_t seed, int64_t count, int32_t* out) {
  tsp_rand_t g;
  tsp_srand(&g, seed);
  for (int64_t i = 0; i < count; i++) out[i] = tsp_rand_next(&g);
}

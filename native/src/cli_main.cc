// tsp-native — standalone CLI over the native runtime (no Python, no JAX).
//
// Drop-in for the reference binary's contract (tsp.cpp:270-368): same four
// positional args (tsp.cpp:282), same usage line / exit 1 on wrong arity
// (tsp.cpp:280-284), same >16-cities scold + exit(1337) (tsp.cpp:289-295),
// same banner/dims lines and machine-parsed final line (tsp.cpp:307,377,363).
// Optional 5th/6th args extend it: ranks (emulated merge-tree shape) and
// seed. Deviations match the framework: n < 3 errors cleanly (SURVEY.md
// quirk #6) instead of hanging or emitting the INT_MAX sentinel.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <vector>

#include "tsp_native.h"

static unsigned long long now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC_RAW, &ts);
  return (unsigned long long)ts.tv_sec * 1000ull +
         (unsigned long long)(ts.tv_nsec / 1000000);
}

int main(int argc, char** argv) {
  unsigned long long start = now_ms();
  if (argc < 5 || argc > 7) {
    // byte-identical to the reference's usage line (tsp.cpp:282); the
    // optional [ranks] [seed] extensions are documented on stderr only
    printf("Usage:  ./tsp numCitiesPerBlock numBlocks gridDimX gridDimY\n");
    fprintf(stderr, "(tsp-native also accepts optional [ranks] [seed])\n");
    return 1;
  }
  int n = atoi(argv[1]);
  int nb = atoi(argv[2]);
  int gx = atoi(argv[3]);
  int gy = atoi(argv[4]);
  int ranks = argc > 5 ? atoi(argv[5]) : 1;
  unsigned seed = argc > 6 ? (unsigned)strtoul(argv[6], nullptr, 10) : 0u;

  if (n > 16) {
    // byte-identical to the reference's scold (tsp.cpp:292) + exit(1337)
    printf(
        "Come on... We don't want to wait forever so lets just have you "
        "retry that with less than 16 cities per block...\n");
    exit(1337);
  }
  if (n < 3) {
    fprintf(stderr,
            "error: blocks need >= 3 cities (got %d): the reference yields "
            "an INT_MAX sentinel for 1 and hangs for 2 (SURVEY.md quirk #6)\n",
            n);
    return 2;
  }
  if (nb < 1 || gx < 1 || gy < 1 || ranks < 1) {
    fprintf(stderr, "error: numBlocks/gridDims/ranks must be positive\n");
    return 2;
  }

  printf("We have %d cities for each of our %d blocks\n", n, nb);
  int32_t rows = 0, cols = 0;
  tsp_blocks_per_dim(nb, &rows, &cols);
  printf("%d blocks in X %d in Y\n", rows, cols);

  double cost = 0.0;
  std::vector<int32_t> tour((size_t)nb * n + 1);
  int32_t tour_len = 0;
  int rc = tsp_run_pipeline(n, nb, gx, gy, seed, ranks, &cost, tour.data(),
                            &tour_len, nullptr);
  if (rc != 0) {
    fprintf(stderr, "error: pipeline failed (rc=%d)\n", rc);
    return 2;
  }
  // the reference's machine-parsed report line (tsp.cpp:363)
  printf("TSP ran in %llu ms for %lu cities and the trip cost %f\n",
         now_ms() - start, (unsigned long)((long)nb * n), cost);
  return 0;
}

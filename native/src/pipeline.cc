/* End-to-end blocked pipeline with rank emulation and tree reduction.
 *
 * Runs what an MPI launch of the reference computes — generate, scatter by
 * the round-robin countdown (tsp.cpp:167-191), solve each block exactly,
 * fold per rank (tsp.cpp:348-352), binary-tree reduce with the reference's
 * shape: a downshift phase for non-power-of-two rank counts then log2
 * rounds with receiver k, sender k + 2^d (tsp.cpp:52-134) — in one process
 * with virtual ranks, the native analog of the single-rank-stub trick
 * (SURVEY.md §4) generalized to any rank count. Matches the JAX
 * rank-emulated path (models/distributed.py) bit for bit.
 *
 * Deviation (shared with the JAX path): the reference's receive buffer is
 * never cleared between tree rounds, corrupting second receives
 * (SURVEY.md quirk #5); here each merge sees its true operands.
 */
#include <cstddef>
#include <cstdint>
#include <vector>

#include "tsp_native.h"

namespace {

struct Tour {
  std::vector<int32_t> ids; /* closed tour of global city ids */
  double cost = 0.0;
  bool empty() const { return ids.empty(); }
};

Tour merge(const double* xy, const Tour& t1, const Tour& t2) {
  if (t2.empty()) return t1; /* idle-rank operand: keep mine */
  if (t1.empty()) return t2;
  Tour out;
  out.ids.resize(t1.ids.size() + t2.ids.size() - 1);
  int32_t out_len = 0;
  out.cost = tsp_merge_tours(xy, t1.ids.data(), (int32_t)t1.ids.size(),
                             t1.cost, t2.ids.data(), (int32_t)t2.ids.size(),
                             t2.cost, out.ids.data(), &out_len);
  out.ids.resize(out_len);
  return out;
}

} /* namespace */

int32_t tsp_run_pipeline(int32_t n, int32_t num_blocks, int32_t grid_dim_x,
                         int32_t grid_dim_y, uint32_t seed, int32_t ranks,
                         double* cost_out, int32_t* tour_out,
                         int32_t* tour_len_out, double* block_costs_out) {
  if (n < 3 || n > 20 || num_blocks < 1 || ranks < 1) return 1;

  std::vector<double> xy((int64_t)num_blocks * n * 2);
  if (tsp_generate(n, num_blocks, grid_dim_x, grid_dim_y, seed, xy.data()))
    return 1;

  /* solve every block exactly; tours carry global city ids */
  std::vector<Tour> blocks(num_blocks);
  std::vector<double> dist((int64_t)n * n);
  std::vector<int32_t> local(n + 1);
  for (int32_t b = 0; b < num_blocks; b++) {
    tsp_distance_matrix(n, xy.data() + (int64_t)b * n * 2, dist.data());
    double c = tsp_solve_block(n, dist.data(), local.data());
    if (c < 0) return 1;
    blocks[b].cost = c;
    blocks[b].ids.resize(n + 1);
    for (int32_t j = 0; j <= n; j++) blocks[b].ids[j] = local[j] + b * n;
    if (block_costs_out) block_costs_out[b] = c;
  }

  /* reference block assignment: counts[r] = #{b in 1..B : b mod P == r},
   * blocks handed out contiguously in rank order (tsp.cpp:167-191) */
  std::vector<int32_t> counts(ranks, 0);
  for (int32_t b = 1; b <= num_blocks; b++) counts[b % ranks]++;

  /* per-rank local fold (tsp.cpp:348-352) */
  std::vector<Tour> per_rank(ranks);
  int32_t start = 0;
  for (int32_t r = 0; r < ranks; r++) {
    Tour acc; /* empty when this rank got zero blocks */
    for (int32_t k = 0; k < counts[r]; k++)
      acc = merge(xy.data(), acc, blocks[start + k]);
    per_rank[r] = acc;
    start += counts[r];
  }

  /* tree reduction, reference shape (tsp.cpp:52-134) */
  int32_t lastpower = 1;
  while (lastpower * 2 <= ranks) lastpower *= 2;
  for (int32_t r = lastpower; r < ranks; r++) /* downshift phase */
    per_rank[r - lastpower] = merge(xy.data(), per_rank[r - lastpower], per_rank[r]);
  for (int32_t stride = 1; stride < lastpower; stride *= 2)
    for (int32_t k = 0; k < lastpower; k += 2 * stride)
      per_rank[k] = merge(xy.data(), per_rank[k], per_rank[k + stride]);

  const Tour& final_tour = per_rank[0];
  if (cost_out) *cost_out = final_tour.cost;
  if (tour_len_out) *tour_len_out = (int32_t)final_tour.ids.size();
  if (tour_out)
    for (std::size_t j = 0; j < final_tour.ids.size(); j++)
      tour_out[j] = final_tour.ids[j];
  return 0;
}

/* Dense Held-Karp exact TSP solver (array-based, no hashing).
 *
 * Clean-room redesign sharing the layout of the JAX kernel
 * (ops/held_karp.py): state (visited-mask over cities 1..n-1, endpoint)
 * maps to a flat [2^(n-1), n-1] table — the array index IS the key,
 * replacing the reference's std::map of composite bit-keys with O(log)
 * lookups (tsp.cpp:409, assignment2.h:146-154). Masks are swept in plain
 * increasing order, which already satisfies the DP dependency
 * (mask \ {b} < mask numerically).
 *
 * Semantics match the verified JAX kernel: cost[0][e] = d(0, e+1);
 * cost[mask][e] = min over b in mask of cost[mask\{b}][b] + d(b+1, e+1)
 * with ties toward the smallest b (strict <, ascending scan — the
 * reference's tie-break, tsp.cpp:457-471); closing pass picks the smallest
 * endpoint on ties. Doubles throughout, contraction disabled in the build,
 * so costs are bit-identical to the oracle.
 */
#include <cmath>
#include <cstdint>
#include <vector>

#include "tsp_native.h"

void tsp_distance_matrix(int32_t n, const double* xy, double* dist) {
  for (int32_t i = 0; i < n; i++) {
    for (int32_t j = 0; j < n; j++) {
      double dx = xy[2 * i] - xy[2 * j];
      double dy = xy[2 * i + 1] - xy[2 * j + 1];
      dist[(int64_t)i * n + j] = std::sqrt(dx * dx + dy * dy);
    }
  }
}

double tsp_solve_block(int32_t n, const double* d, int32_t* tour) {
  if (n < 3 || n > 20) return -1.0;
  const int32_t m = n - 1;
  const uint32_t full = ((uint32_t)1 << m) - 1;
  const int64_t states = (int64_t)(full + 1) * m;
  const double inf = 1.0 / 0.0;

  std::vector<double> cost(states, inf);
  std::vector<int8_t> parent(states, -1);

  for (int32_t e = 0; e < m; e++) cost[e] = d[e + 1]; /* d(0, e+1), mask 0 */

  for (uint32_t mask = 1; mask <= full; mask++) {
    const int64_t base = (int64_t)mask * m;
    for (int32_t e = 0; e < m; e++) {
      if (mask & ((uint32_t)1 << e)) continue; /* endpoint outside the mask */
      double best = inf;
      int8_t bp = -1;
      const double* de = d + (int64_t)1 * n; /* row of city b+1 starts at d[(b+1)*n] */
      for (int32_t b = 0; b < m; b++) {
        if (!(mask & ((uint32_t)1 << b))) continue;
        double c = cost[(int64_t)(mask ^ ((uint32_t)1 << b)) * m + b] +
                   de[(int64_t)b * n + (e + 1)];
        if (c < best) { /* strict <: first (smallest b) minimum wins */
          best = c;
          bp = (int8_t)b;
        }
      }
      cost[base + e] = best;
      parent[base + e] = bp;
    }
  }

  /* close the tour back to city 0 (tsp.cpp:483-499 semantics) */
  double best_total = inf;
  int32_t best_e = 0;
  for (int32_t e = 0; e < m; e++) {
    double t = cost[(int64_t)(full ^ ((uint32_t)1 << e)) * m + e] +
               d[(int64_t)(e + 1) * n];
    if (t < best_total) {
      best_total = t;
      best_e = e;
    }
  }

  /* backtrack parent pointers newest-to-oldest */
  tour[0] = 0;
  tour[n] = 0;
  uint32_t mask = full ^ ((uint32_t)1 << best_e);
  int32_t e = best_e;
  for (int32_t pos = n - 1; pos >= 1; pos--) {
    tour[pos] = e + 1;
    int8_t p = parent[(int64_t)mask * m + e];
    if (p < 0) break; /* mask exhausted (pos == 1) */
    mask &= ~((uint32_t)1 << p);
    e = p;
  }
  return best_total;
}

/* Tour-merge operator: minimal 2-opt edge swap between two closed tours.
 *
 * Same replicated semantics as the JAX twin (ops/merge.py, verified
 * bit-exact vs goldens), without the reference's O(n1*n2) vector-rotate
 * scan (tsp.cpp:212-227) — edges are addressed by index instead:
 *  - all len1 x len2 edge pairs are scored with swapPairCost
 *    (tsp.cpp:197-200) in its left-to-right addition order;
 *  - the first minimum in i-major, j-minor order wins (strict <);
 *  - tour 2 is spliced REVERSED after the first city of tour 1 whose id
 *    matches either endpoint of the chosen left edge (tsp.cpp:244-259),
 *    rotated so the chosen right-edge head lands at the boundary;
 *  - the merged cost is formulaic — cost1 + cost2 + best_swap — and the
 *    spliced path is never re-measured (SURVEY.md quirk #4).
 */
#include <cmath>

#include "tsp_native.h"

static inline double dist2(const double* xy, int32_t a, int32_t b) {
  double dx = xy[2 * a] - xy[2 * b];
  double dy = xy[2 * a + 1] - xy[2 * b + 1];
  return std::sqrt(dx * dx + dy * dy);
}

double tsp_merge_tours(const double* xy, const int32_t* ids1, int32_t len1,
                       double cost1, const int32_t* ids2, int32_t len2,
                       double cost2, int32_t* out, int32_t* out_len) {
  const double inf = 1.0 / 0.0;
  double best = inf;
  int32_t bi = 0, bj = 0;
  for (int32_t i = 0; i < len1; i++) {
    int32_t a = ids1[i];
    int32_t b = ids1[(i + 1 >= len1) ? 0 : i + 1];
    double d_ab = dist2(xy, a, b);
    for (int32_t j = 0; j < len2; j++) {
      int32_t r1 = ids2[j];
      int32_t r2 = ids2[(j + 1 >= len2) ? 0 : j + 1];
      /* swapPairCost order: ((d(a,r2) + d(b,r1)) - d(a,b)) - d(r1,r2) */
      double sc =
          ((dist2(xy, a, r2) + dist2(xy, b, r1)) - d_ab) - dist2(xy, r1, r2);
      if (sc < best) {
        best = sc;
        bi = i;
        bj = j;
      }
    }
  }

  const int32_t l2p = len2 - 1; /* tour 2 with the closing duplicate popped */
  const int32_t p2rot = (bj >= l2p) ? 0 : bj;
  const int32_t a_id = ids1[bi];
  const int32_t b_id = ids1[(bi + 1 >= len1) ? 0 : bi + 1];

  int32_t q = 0; /* first position matching either chosen-edge endpoint */
  while (q < len1 && ids1[q] != a_id && ids1[q] != b_id) q++;

  int32_t pos = 0;
  for (int32_t t = 0; t <= q; t++) out[pos++] = ids1[t];
  for (int32_t u = 0; u < l2p; u++)
    out[pos++] = ids2[((p2rot - u) % l2p + l2p) % l2p];
  for (int32_t t = q + 1; t < len1; t++) out[pos++] = ids1[t];
  *out_len = len1 + l2p;
  return (cost1 + cost2) + best;
}

/* tsp_native — native C++ runtime for the TPU-TSP framework.
 *
 * This is the framework's host-side native layer (the analog of the
 * reference's C++/MPI runtime, tsp.cpp + assignment2.h): a bit-exact
 * instance generator (glibc-rand replica), a dense array-based Held-Karp
 * solver, the 2-opt tour-merge operator, and the full rank-emulated
 * pipeline with the reference's binary-tree reduction shape
 * (tsp.cpp:52-134). It serves as
 *
 *  - the self-contained CPU oracle (goldens can be regenerated and parity
 *    checked without the upstream sources present), and
 *  - the fast host path behind the CLI's --backend=native.
 *
 * Design is clean-room and array-first: the DP table is a dense
 * [2^(n-1), n-1] array indexed by (visited-mask, endpoint) — the same
 * layout as the JAX kernel (ops/held_karp.py) — not the reference's
 * std::map of composite keys (tsp.cpp:409). All floating-point runs in
 * strict double with contraction disabled so results are bit-identical to
 * the Python/numpy path and to a glibc build of the reference.
 */
#ifndef TSP_NATIVE_H
#define TSP_NATIVE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- glibc TYPE_3 rand() replica (determinism root; tsp.cpp:273) ---- */

typedef struct {
  uint32_t window[31]; /* ring of the last 31 words */
  int pos;             /* index of the oldest word (lag 31) */
} tsp_rand_t;

void tsp_srand(tsp_rand_t* g, uint32_t seed);
int32_t tsp_rand_next(tsp_rand_t* g);
/* Fill out[0..count) with successive rand() outputs from `seed`. */
void tsp_rand_stream(uint32_t seed, int64_t count, int32_t* out);

/* ---- instance generation (tsp.cpp:136-157, 373-403 semantics) ---- */

/* Near-square factorization; writes rows/cols. */
void tsp_blocks_per_dim(int32_t num_blocks, int32_t* rows, int32_t* cols);

/* Generate num_blocks blocks of n cities each into xy[b*n*2 + j*2 + {0,1}]
 * (block-major, city-minor, x then y — generation order == rand order).
 * Returns 0 on success, nonzero on bad arguments. */
int32_t tsp_generate(int32_t num_cities_per_block, int32_t num_blocks,
                     int32_t grid_dim_x, int32_t grid_dim_y, uint32_t seed,
                     double* xy);

/* ---- exact per-block solver (dense Held-Karp) ---- */

/* Exact TSP over one block given its dense [n, n] distance matrix.
 * Writes the closed tour (block-local indices, tour[0]==tour[n]==0) into
 * tour[0..n]. Returns the optimal cost; ties break toward the smallest
 * predecessor index (matching the JAX kernel and the reference's strict-<
 * ascending scan). n must be in [3, 20]. Returns -1.0 on bad n. */
double tsp_solve_block(int32_t n, const double* dist, int32_t* tour);

/* Dense Euclidean distance matrix from xy[n*2] into dist[n*n]. */
void tsp_distance_matrix(int32_t n, const double* xy, double* dist);

/* ---- tour-merge operator (tsp.cpp:197-269 semantics) ---- */

/* Merge closed tour 2 into closed tour 1 by the minimal 2-opt edge swap.
 * Distances are computed from global coordinates xy[>=max_id*2].
 * out must hold len1 + len2 - 1 entries; *out_len receives that length.
 * Returns the (formulaic) merged cost cost1 + cost2 + best_swap.
 * Both operands must hold >= 3 distinct cities. */
double tsp_merge_tours(const double* xy, const int32_t* ids1, int32_t len1,
                       double cost1, const int32_t* ids2, int32_t len2,
                       double cost2, int32_t* out, int32_t* out_len);

/* ---- full pipeline (generate -> solve -> fold -> tree reduce) ---- */

/* Run the blocked pipeline end to end, emulating `ranks` MPI ranks with
 * the reference's block assignment (tsp.cpp:167-191) and binary-tree
 * reduction shape (tsp.cpp:52-134).
 *
 * Outputs (any may be NULL to skip):
 *   cost_out        final tour cost (rank-0 result)
 *   tour_out        closed global tour, capacity num_blocks*n + 1
 *   tour_len_out    number of valid entries in tour_out
 *   block_costs_out per-block optimal costs, capacity num_blocks
 * Returns 0 on success; 1 on bad arguments. */
int32_t tsp_run_pipeline(int32_t num_cities_per_block, int32_t num_blocks,
                         int32_t grid_dim_x, int32_t grid_dim_y, uint32_t seed,
                         int32_t ranks, double* cost_out, int32_t* tour_out,
                         int32_t* tour_len_out, double* block_costs_out);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* TSP_NATIVE_H */

/* Blocked-instance generator, bit-exact vs the reference's semantics.
 *
 * Replicated behavior (quirks intentional; see SURVEY.md §5 and
 * ops/generator.py, the Python twin of this file):
 *  - getBlocksPerDim (tsp.cpp:136-157): perfect square -> sqrt x sqrt,
 *    else smallest divisor >= 2 times cofactor.
 *  - distributeCities (tsp.cpp:373-403): block i of rows x cols has
 *    row = i / rows and col = cols - (i % cols) - 1; each city draws x
 *    then y through fRand (assignment2.h:86-91).
 *  - float32 spacing quirk (tsp.cpp:378-379): the per-block spacing and
 *    the row/col products are C `float`; only the final fRand mix runs in
 *    double. Reproduced with explicit float casts.
 *  - grid-spill quirk (SURVEY.md quirk #3): non-square factorizations
 *    scale `row` (which ranges up to cols-1) by gridDimX/rows, placing
 *    cities outside the nominal grid. Reproduced faithfully.
 */
#include <cmath>

#include "tsp_native.h"

void tsp_blocks_per_dim(int32_t num_blocks, int32_t* rows, int32_t* cols) {
  if (num_blocks < 1) { /* divisor scan below never terminates for <= 0 */
    *rows = *cols = 0;
    return;
  }
  double s = std::sqrt((double)num_blocks);
  if (s - std::floor(s) == 0.0) { /* ISSQUARE, assignment2.h:11 */
    *rows = *cols = (int32_t)s;
    return;
  }
  int32_t d = 2;
  while (num_blocks % d != 0) d++;
  *rows = d;
  *cols = num_blocks / d;
}

static inline double frand01(tsp_rand_t* g) {
  return (double)tsp_rand_next(g) / (double)2147483647;
}

int32_t tsp_generate(int32_t n, int32_t num_blocks, int32_t grid_dim_x,
                     int32_t grid_dim_y, uint32_t seed, double* xy) {
  if (n < 1 || num_blocks < 1 || !xy) return 1;
  int32_t rows, cols;
  tsp_blocks_per_dim(num_blocks, &rows, &cols);

  float xspb = (float)grid_dim_x / (float)rows;
  float yspb = (float)grid_dim_y / (float)cols;

  tsp_rand_t g;
  tsp_srand(&g, seed);
  for (int32_t i = 0; i < num_blocks; i++) {
    int32_t row = i / rows;              /* tsp.cpp:391 */
    int32_t col = cols - (i % cols) - 1; /* tsp.cpp:393 */
    double x_lo = (double)((float)row * xspb);
    double x_hi = (double)((float)(row + 1) * xspb);
    double y_lo = (double)((float)col * yspb);
    double y_hi = (double)((float)(col + 1) * yspb);
    for (int32_t j = 0; j < n; j++) {
      double fx = frand01(&g); /* x before y, city-minor (tsp.cpp:394-395) */
      double fy = frand01(&g);
      xy[((int64_t)i * n + j) * 2 + 0] = x_lo + fx * (x_hi - x_lo);
      xy[((int64_t)i * n + j) * 2 + 1] = y_lo + fy * (y_hi - y_lo);
    }
  }
  return 0;
}

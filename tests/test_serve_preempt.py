"""Iteration-level continuous batching (ISSUE 13): preemptible B&B
slices, admission control, and the surfaces that ride along.

The core guarantee under test: a B&B proof preempted at arbitrary slice
boundaries and resumed from its donated checkpoint converges to the SAME
incumbent, certified lower bound, and tour as one uninterrupted call —
single-rank and sharded, and even when a checkpoint write is torn by an
injected fault mid-flight. Everything the scheduler/ladder learned from
the preemption (partial-latency evidence, queue-age stamps, SLO burn)
has its own unit coverage here.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.obs import metrics as obs_metrics
from tsp_mpi_reduction_tpu.obs.slo import BurnMeter
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.serve.ladder import DeadlineLadder, LatencyEstimator
from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

#: the shared proof instance: n=12 integer-rounded Euclidean with the
#: min-out bound and a deliberately small frontier, so the search runs
#: hundreds of expansion steps (many preemption boundaries) yet proves
#: in well under a second per leg
N, SEED = 12, 33
SOLVE_KW = dict(capacity=256, k=8, inner_steps=1, bound="min-out",
                mst_prune=False, node_ascent=0, device_loop=False)


def _d() -> np.ndarray:
    rng = np.random.default_rng(SEED)
    return np.rint(distance_matrix_np(rng.uniform(0, 100, (N, 2))) * 10)


def _slice_to_proof(d, path, slice_s=0.02, max_slices=400):
    """Drive solve_slice to proof, returning (result, slices_taken)."""
    res, handle = bb.solve_slice(d, slice_s, checkpoint_path=path, **SOLVE_KW)
    slices = 1
    while handle is not None:
        assert slices < max_slices, "sliced solve failed to converge"
        res, handle = bb.solve_slice(d, slice_s, handle, **SOLVE_KW)
        slices += 1
    return res, slices


# -- preempt/resume bit-identity -----------------------------------------------


def test_solve_slice_bit_identical_vs_uninterrupted(tmp_path):
    """A proof cut into ~dozens of slices through the donated-checkpoint
    path lands EXACTLY where the uninterrupted search lands: same proven
    incumbent, same certified LB, same tour. The slice boundaries are
    wall-clock (non-deterministic cut points), so this holds only
    because the restore is bit-exact and the DFS order deterministic."""
    d = _d()
    ref = bb.solve(d, **SOLVE_KW)
    assert ref.proven_optimal
    res, slices = _slice_to_proof(d, str(tmp_path / "slice.npz"))
    assert slices >= 2, "instance proved in one slice — nothing preempted"
    assert res.proven_optimal
    assert res.cost == ref.cost
    assert res.lower_bound == ref.lower_bound
    assert np.array_equal(res.tour, ref.tour)


def test_solve_slice_first_slice_requires_checkpoint_path():
    with pytest.raises(ValueError, match="checkpoint_path"):
        bb.solve_slice(_d(), 0.05, **SOLVE_KW)


def test_solve_slice_handle_reports_progress(tmp_path):
    """An unproven slice returns a ResumeHandle whose gap_progress is a
    sane [0, 1] fraction and whose elapsed accumulates across slices —
    the evidence the ladder's partial-latency estimator consumes."""
    d = _d()
    res, handle = bb.solve_slice(
        d, 1e-3, checkpoint_path=str(tmp_path / "h.npz"), **SOLVE_KW
    )
    if handle is None:
        pytest.skip("instance proved inside the first tiny slice")
    assert handle.slices == 1
    assert handle.elapsed_s > 0
    assert 0.0 <= handle.gap_progress() <= 1.0
    _, h2 = bb.solve_slice(d, 1e-3, handle, **SOLVE_KW)
    if h2 is not None:
        assert h2.slices == 2
        assert h2.elapsed_s > handle.elapsed_s


def test_sharded_chunked_resume_bit_identical():
    """The sharded analog: a proof preempted into max_iters chunks via
    checkpoint/resume on a 4-rank virtual mesh converges bit-identically
    to the uninterrupted sharded solve, with a monotone certified LB
    across every chunk."""
    import tempfile

    from test_bnb import make_rank_mesh

    d = _d()
    mesh = make_rank_mesh(4)
    kw = dict(capacity_per_rank=256, k=8, inner_steps=1, bound="min-out",
              mst_prune=False)
    ref = bb.solve_sharded(d, mesh, **kw)
    assert ref.proven_optimal
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "shard.npz")
        floors, res = [], None
        for _chunk in range(60):
            resume = ck if os.path.exists(ck) else None
            res = bb.solve_sharded(d, mesh, max_iters=40, checkpoint_path=ck,
                                   resume_from=resume, **kw)
            floors.append(res.lower_bound)
            if res.proven_optimal:
                break
    assert res is not None and res.proven_optimal
    assert len(floors) >= 2, "proof fit one chunk — nothing resumed"
    assert floors == sorted(floors)
    assert res.cost == ref.cost
    assert res.lower_bound == ref.lower_bound
    assert np.array_equal(res.tour, ref.tour)


@pytest.mark.chaos
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_solve_slice_survives_torn_checkpoint_write(tmp_path):
    """ckpt.write:truncate mid-proof: the slice whose snapshot publish
    is torn dies with FaultInjected; the retry resumes from the NEWEST
    VALID snapshot (fallback_restores counts it) and the proof still
    lands bit-identical to the uninterrupted search."""
    from tsp_mpi_reduction_tpu.resilience import faults
    from tsp_mpi_reduction_tpu.resilience.faults import FaultInjected
    from tsp_mpi_reduction_tpu.resilience.health import HEALTH

    d = _d()
    ref = bb.solve(d, **SOLVE_KW)
    path = str(tmp_path / "torn.npz")
    faults.configure("ckpt.write:truncate,nth=3,seed=5")
    try:
        h0 = HEALTH.snapshot()
        res, handle, slices, crashes = None, None, 0, 0
        for _ in range(400):
            try:
                if res is None:
                    res, handle = bb.solve_slice(
                        d, 0.02, checkpoint_path=path, **SOLVE_KW
                    )
                else:
                    res, handle = bb.solve_slice(d, 0.02, handle, **SOLVE_KW)
                slices += 1
            except FaultInjected:
                crashes += 1  # the slice died mid-publish; supervisor retries
                continue
            if handle is None:
                break
        hits = faults.registry().hits("ckpt.write")
    finally:
        faults.clear()
    assert hits > 0, "ckpt.write seam never crossed"
    assert crashes >= 1
    assert res is not None and res.proven_optimal
    assert HEALTH.snapshot()["fallback_restores"] > h0["fallback_restores"]
    assert res.cost == ref.cost
    assert res.lower_bound == ref.lower_bound
    assert np.array_equal(res.tour, ref.tour)


# -- the scheduler's iteration-level loop --------------------------------------


def test_submit_bnb_preempts_resumes_and_interleaves_hk(tmp_path):
    """One proof on the device loop with HK tickets arriving mid-flight:
    the proof is preempted at slice boundaries (counted + re-queued),
    the HK batch is admitted into the gaps, and the final job result is
    the proven optimum — identical to a direct solve."""
    d = _d()
    ref = bb.solve(d, **SOLVE_KW)
    rng = np.random.default_rng(9)
    hk_d = distance_matrix_np(rng.uniform(0, 100, (8, 2)))
    with MicroBatchScheduler(max_batch=8, max_wait_ms=5.0) as sched:
        job = sched.submit_bnb(
            d, budget_s=60.0, slice_s=0.02,
            checkpoint_path=str(tmp_path / "job.npz"), solve_kw=SOLVE_KW,
        )
        # tickets submitted while the proof holds the device: they must
        # be answered from the admit gaps, not after the proof
        tickets = [sched.submit(hk_d[None]) for _ in range(3)]
        got = [t.wait(timeout=60.0) for t in tickets]
        res = job.wait(timeout=60.0)
        stats = sched.stats()
    assert all(g is not None for g in got)
    assert res is not None and res.proven_optimal
    assert res.cost == ref.cost
    assert np.array_equal(res.tour, ref.tour)
    assert stats["bnb_jobs"] == 1
    assert stats["bnb_slices"] >= 2
    assert stats["bnb_preemptions"] >= 1
    assert stats["bnb_resumes"] >= 1
    assert job.preemptions >= 1 and job.resumes >= 1


def test_submit_bnb_validation_is_synchronous(tmp_path):
    with MicroBatchScheduler(max_batch=4) as sched:
        with pytest.raises(ValueError, match="distance matrix"):
            sched.submit_bnb(np.ones((3, 4)), budget_s=1.0, slice_s=0.1,
                             checkpoint_path=str(tmp_path / "x.npz"))
        with pytest.raises(ValueError, match="n >= 3"):
            sched.submit_bnb(np.ones((2, 2)), budget_s=1.0, slice_s=0.1,
                             checkpoint_path=str(tmp_path / "x.npz"))
        with pytest.raises(ValueError, match="must be > 0"):
            sched.submit_bnb(np.ones((4, 4)), budget_s=0.0, slice_s=0.1,
                             checkpoint_path=str(tmp_path / "x.npz"))
        with pytest.raises(ValueError, match="checkpoint_path"):
            sched.submit_bnb(np.ones((4, 4)), budget_s=1.0, slice_s=0.1,
                             checkpoint_path="")


def test_ticket_queue_age_stamped_at_flush():
    """The worker stamps every flushed ticket's queue wait — the number
    the ladder subtracts so its EWMA learns service time (and the
    serve_queue_age_seconds histogram observes)."""
    rng = np.random.default_rng(2)
    d = distance_matrix_np(rng.uniform(0, 100, (8, 2)))
    before = obs_metrics.REGISTRY.snapshot(prefix="serve_queue_age_seconds")
    with MicroBatchScheduler(max_batch=2, max_wait_ms=2.0) as sched:
        t = sched.submit(d[None])
        assert t.wait(timeout=30.0) is not None
    assert t.queue_age_s is not None and t.queue_age_s >= 0.0
    delta = obs_metrics.REGISTRY.delta(
        before, prefix="serve_queue_age_seconds"
    )
    series = delta.data.get("serve_queue_age_seconds", {}).get("series", {})
    counts = [
        h["count"] for h in series.values() if isinstance(h, dict)
    ]
    assert sum(counts) >= 1


# -- ladder learning -----------------------------------------------------------


def test_estimator_observe_partial_projects_full_cost():
    """A rung preempted at 25% gap closure after 1s teaches ~4s — the
    projection — not the 1s it was allowed to run; zero progress is
    clamped to cap_factor x elapsed, not infinity."""
    est = LatencyEstimator()
    est.observe_partial("bnb", 12, 1.0, 0.25)
    assert est.estimate("bnb", 12, 0.0) == pytest.approx(4.0)
    est2 = LatencyEstimator()
    est2.observe_partial("bnb", 12, 1.0, 0.0, cap_factor=64.0)
    assert est2.estimate("bnb", 12, 0.0) == pytest.approx(64.0)
    est3 = LatencyEstimator()
    est3.observe_partial("bnb", 12, 0.0, 0.5)  # no elapsed: no evidence
    assert est3.estimate("bnb", 12, -1.0) == -1.0


def test_attempt_feeds_service_time_not_queue_wait():
    """_attempt subtracts the rung's scheduler queue wait before feeding
    the EWMA: one head-of-line episode must not pin later tight-deadline
    requests to greedy after the queue has drained. A rung that TIMES
    OUT keeps its full elapsed (the budget was really burned)."""
    ladder = DeadlineLadder(scheduler=None)

    def run_with_wait():
        time.sleep(0.03)
        ladder._tls.queue_wait = 10.0  # pretend it all sat in the queue
        return "ok"

    assert ladder._attempt("pipeline", 8, run_with_wait) == "ok"
    # elapsed (~30 ms) minus claimed queue wait clamps to ~0 service time
    assert ladder.estimator.estimate("pipeline", 8, 99.0) < 0.01

    ladder2 = DeadlineLadder(scheduler=None)

    def run_timeout():
        time.sleep(0.03)
        return None  # rung timed out: no ticket, no queue-wait stamp

    assert ladder2._attempt("pipeline", 8, run_timeout) is None
    assert ladder2.estimator.estimate("pipeline", 8, 0.0) >= 0.03


# -- SLO burn meter ------------------------------------------------------------


def test_burn_meter_no_verdict_below_min_count():
    bm = BurnMeter({"greedy": {"target_ms": 50.0, "goal": 0.9}}, min_count=4)
    for _ in range(3):
        bm.observe("greedy", 1.0)
    assert bm.burn("greedy") is None  # no shedding on no evidence
    assert bm.burn("unknown-tier") is None
    snap = bm.snapshot()
    assert snap["greedy"] == {"requests": 3, "burn_rate": None}


def test_burn_meter_burn_rate_and_window_rolloff():
    bm = BurnMeter(
        {"greedy": {"target_ms": 50.0, "goal": 0.9}}, window=8, min_count=4
    )
    # 4 misses out of 4: miss fraction 1.0 over budget 0.1 -> burn 10x
    for _ in range(4):
        bm.observe("greedy", 1.0)
    assert bm.burn("greedy") == pytest.approx(10.0)
    # 8 fast answers roll every miss out of the window -> burn 0
    for _ in range(8):
        bm.observe("greedy", 0.001)
    assert bm.burn("greedy") == pytest.approx(0.0)
    assert bm.snapshot()["greedy"]["requests"] == 8


def test_burn_meter_rejects_bad_window():
    with pytest.raises(ValueError, match="window"):
        BurnMeter(window=0)


# -- queue-age histogram quantiles ---------------------------------------------


def test_hist_quantile_interpolates_and_clamps():
    hist = {"count": 10, "buckets": [1.0, 2.0, 4.0], "counts": [5, 5, 0]}
    assert obs_metrics.hist_quantile(hist, 0.5) == pytest.approx(1.0)
    # rank 7.5 of 10: 2.5 into the 5-count (1, 2] bucket -> 1.5
    assert obs_metrics.hist_quantile(hist, 0.75) == pytest.approx(1.5)
    assert obs_metrics.hist_quantile(hist, 1.0) == pytest.approx(2.0)
    assert obs_metrics.hist_quantile({"count": 0}, 0.5) is None
    assert obs_metrics.hist_quantile(hist, 0.0) is None
    assert obs_metrics.hist_quantile(hist, 1.5) is None
    # +Inf-bucket observations clamp to the last finite edge
    tail = {"count": 4, "buckets": [1.0, 2.0], "counts": [1, 0]}
    assert obs_metrics.hist_quantile(tail, 0.99) == pytest.approx(2.0)


# -- stats JSON + report tool --------------------------------------------------


def test_service_stats_admission_block(tmp_path, capsys):
    """The service's stats JSON carries the admission block (per-tier
    burn, preemption counters, queue-age percentiles) and obs_report
    --serve renders it; a payload without one is exit 2."""
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, SolveService

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import obs_report

    rng = np.random.default_rng(4)
    with SolveService(ServiceConfig(max_batch=4, max_wait_ms=2.0)) as svc:
        for i in range(5):
            resp = svc.handle({
                "id": i, "xy": rng.uniform(0, 100, (8, 2)).tolist(),
                "deadline_ms": 900.0,
            })
            assert "error" not in resp
        stats_line = svc.stats_json()
    adm = json.loads(stats_line)["admission"]
    assert set(adm) >= {
        "burn", "slo_sheds", "preemptions", "resumes", "admit_flushes",
        "queue_age_s",
    }
    assert adm["burn"]["pipeline"]["requests"] == 5
    assert adm["queue_age_s"]["count"] >= 5
    assert adm["queue_age_s"]["p50"] is not None

    good = tmp_path / "serve_stats.json"
    good.write_text(stats_line + "\n")
    assert obs_report.main(["--serve", str(good)]) == 0
    out = capsys.readouterr().out
    assert "burn pipeline:" in out and "queue age:" in out
    # a pre-iteration-level payload (no admission block) is exit 2
    bad = tmp_path / "old_stats.json"
    bad.write_text(json.dumps({"responses": 1, "cache": {}}) + "\n")
    assert obs_report.main(["--serve", str(bad)]) == 2

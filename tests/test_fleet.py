"""Fleet unit suite (ISSUE 11): shared cache tier + supervisor/front.

The shared-tier tests run in-process (two cache instances over one
directory ARE two replicas as far as the disk tier is concerned). The
supervisor/front tests use the stub replica (``fleet_stub_replica.py``)
— the real-serve-subprocess paths are covered by ``test_fleet_chaos.py``
so these stay fast enough for tier-1.
"""

import io
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.fleet import (
    FleetConfig,
    FleetFront,
    ReplicaSpec,
    SharedCacheTier,
    TieredSolutionCache,
)
from tsp_mpi_reduction_tpu.fleet.supervisor import SupervisorConfig
from tsp_mpi_reduction_tpu.resilience.health import HEALTH
from tsp_mpi_reduction_tpu.serve.cache import CacheEntry
from tsp_mpi_reduction_tpu.serve.service import run_jsonl

pytestmark = [
    pytest.mark.fleet,
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    ),
]

STUB = os.path.join(os.path.dirname(__file__), "fleet_stub_replica.py")


def _entry(cost, tier="greedy", gap=None, n=6):
    tour = np.concatenate([np.arange(n, dtype=np.int32), [0]])
    return CacheEntry(cost=float(cost), tour=tour, certified_gap=gap, tier=tier)


def _stub_specs(count, env_extra=None, **spec_kw):
    env = dict(os.environ)
    env.update(env_extra or {})
    return [
        ReplicaSpec(argv=[sys.executable, STUB], env=env, scrape=False, **spec_kw)
        for _ in range(count)
    ]


def _fast_cfg(tmp_path, specs, **kw):
    sup = kw.pop("supervisor", None) or SupervisorConfig(
        probe_interval_s=0.05,
        wedge_timeout_s=1.0,
        startup_grace_s=0.5,
        restart_backoff_base_s=0.05,
        restart_backoff_max_s=0.3,
        healthy_reset_s=2.0,
    )
    return FleetConfig(
        threads=kw.pop("threads", 4),
        shared_cache_dir=str(tmp_path / "shared"),
        compile_cache_dir=str(tmp_path / "cc"),
        replica_specs=specs,
        hop_timeout_s=kw.pop("hop_timeout_s", 5.0),
        supervisor=sup,
        **kw,
    )


def _requests(count, n=6, seed=0, deadline_ms=5000.0):
    rng = np.random.default_rng(seed)
    return [
        {"id": f"r{i}", "xy": rng.uniform(0, 100, (n, 2)).tolist(),
         "deadline_ms": deadline_ms}
        for i in range(count)
    ]


def _run(front, requests):
    out = io.StringIO()
    run_jsonl([json.dumps(r) + "\n" for r in requests], out, service=front)
    return [json.loads(ln) for ln in out.getvalue().strip().splitlines()]


def _assert_valid(resp, n):
    assert "error" not in resp, resp
    tour = resp["tour"]
    assert tour[0] == tour[-1] and sorted(tour[:-1]) == list(range(n))


# -- shared disk cache tier ----------------------------------------------------


def test_shared_tier_cross_instance_roundtrip(tmp_path):
    """Two tier instances over one directory = two replicas: an entry
    published by one is a (promoted) hit in the other, fields intact."""
    a = TieredSolutionCache(8, str(tmp_path))
    b = TieredSolutionCache(8, str(tmp_path))
    entry = _entry(42.0, tier="bnb", gap=0.0)
    a.put("k1", entry)
    got = b.get("k1")
    assert got is not None
    assert got.cost == 42.0 and got.tier == "bnb" and got.certified_gap == 0.0
    assert np.array_equal(got.tour, entry.tour)
    # the promotion filled b's L1: a second get is a pure L1 hit
    assert b.get("k1") is not None
    assert b.shared.stats()["hits"] == 1


def test_shared_tier_better_entry_arbitration(tmp_path):
    """PR 3's replacement policy across processes: a certified optimum
    survives later weaker publishes; a strictly cheaper tour wins."""
    tier = SharedCacheTier(str(tmp_path))
    tier.put("k", _entry(10.0, tier="bnb", gap=0.0))
    tier.put("k", _entry(10.0, tier="greedy"))   # worse: no certificate
    assert tier.get("k").tier == "bnb"
    tier.put("k", _entry(8.0, tier="greedy"))    # cheaper: wins anyway
    assert tier.get("k").cost == 8.0
    stats = tier.stats()
    assert stats["publishes"] == 2 and stats["kept_better"] == 1


def test_shared_tier_concurrent_publishers_always_valid(tmp_path):
    """N threads racing the same canonical key: every read during and
    after the race parses (atomic publish — no torn images), and the
    final entry is one of the published ones with the best cost."""
    tier = SharedCacheTier(str(tmp_path))
    costs = [50.0 - i for i in range(10)]
    barrier = threading.Barrier(10)

    def publish(c):
        barrier.wait()
        SharedCacheTier(str(tmp_path)).put("k", _entry(c))

    threads = [threading.Thread(target=publish, args=(c,)) for c in costs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = tier.get("k")
    assert final is not None and final.cost in costs
    # better-entry arbitration converges on a re-publish of the best
    tier.put("k", _entry(min(costs)))
    assert tier.get("k").cost == min(costs)
    assert tier.stats()["corrupt_skipped"] == 0


@pytest.mark.parametrize("mangle", ["truncate", "corrupt", "garbage"])
def test_shared_tier_torn_entry_reads_as_miss(tmp_path, mangle):
    """A torn/bit-rotted/garbage entry file is a MISS (counted), never a
    wrong tour or an exception — the read_with_fallback posture."""
    tier = SharedCacheTier(str(tmp_path))
    tier.put("k", _entry(9.0))
    path = tier._path("k")
    blob = open(path, "rb").read()
    if mangle == "truncate":
        open(path, "wb").write(blob[: len(blob) // 2])
    elif mangle == "corrupt":
        mutated = bytearray(blob)
        mutated[len(mutated) // 2] ^= 0xFF
        open(path, "wb").write(bytes(mutated))
    else:
        open(path, "wb").write(b"not a checkpoint at all")
    assert tier.get("k") is None
    assert tier.stats()["corrupt_skipped"] == 1
    # a fresh publish heals the entry
    tier.put("k", _entry(7.0))
    assert tier.get("k").cost == 7.0


def test_certified_entry_survives_degraded_resubmit_across_replicas(tmp_path):
    """ISSUE satellite: replica A certifies an instance; replica B gets a
    deadline-degraded resubmission of it (permuted + translated) and
    must answer from the shared tier with the certificate intact — and
    B's own later greedy publish must not clobber the certified entry."""
    from tsp_mpi_reduction_tpu.serve.ladder import LadderConfig
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, SolveService

    rng = np.random.default_rng(7)
    xy = rng.uniform(0, 100, (8, 2))
    mk = lambda: ServiceConfig(  # noqa: E731
        shared_cache_dir=str(tmp_path), threads=2,
        ladder=LadderConfig(bnb_max_n=0),
    )
    with SolveService(mk()) as a:
        r1 = a.handle({"id": "a", "xy": xy.tolist(), "deadline_ms": 60_000.0})
    assert r1["certified_gap"] == 0.0 and r1["tier"] == "pipeline"
    resub = xy[rng.permutation(8)] + 123.0
    with SolveService(mk()) as b:
        r2 = b.handle({"id": "b", "xy": resub.tolist(), "deadline_ms": 0.5})
        stats = json.loads(b.stats_json())
    assert r2["cache"] == "hit" and r2["tier"] == "pipeline"
    assert r2["certified_gap"] == 0.0
    assert abs(r2["cost"] - r1["cost"]) < 1e-6
    assert stats["cache"]["shared"]["hits"] == 1


def test_shared_tier_survives_l1_eviction(tmp_path):
    """The disk tier outlives the L1: an entry evicted from a tiny L1 is
    still served (and re-promoted) from disk."""
    tier = TieredSolutionCache(1, str(tmp_path))
    tier.put("k1", _entry(1.0))
    tier.put("k2", _entry(2.0))  # evicts k1 from the 1-slot L1
    assert tier.get("k1") is not None  # disk hit
    assert tier.shared.stats()["hits"] >= 1


# -- supervisor + front over stub replicas -------------------------------------


def test_fleet_basic_workload_exactly_once(tmp_path):
    front = FleetFront(_fast_cfg(tmp_path, _stub_specs(2)))
    try:
        reqs = _requests(12)
        responses = _run(front, reqs)
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    assert [r["id"] for r in responses] == [r["id"] for r in reqs]  # order kept
    for r in responses:
        _assert_valid(r, 6)
        assert "fleet_latency_ms" in r
    assert stats["responses"] == 12 and stats["fleet"]["alive"] == 2


def test_replica_death_restart_and_redispatch(tmp_path):
    """A replica crashing mid-stream: its in-flight requests re-dispatch
    to the survivor (exactly-once, all valid), the supervisor restarts
    it with bounded backoff, and both actions land in health + stats."""
    # the dying replica is FAST (it answers, attracts the next dispatch
    # into its stdin, then exits with it in flight — a deterministic
    # mid-flight death); the survivor is slow enough to stay busy
    specs = _stub_specs(1, env_extra={"STUB_DIE_AFTER": "2", "STUB_SLEEP_MS": "20"})
    specs += _stub_specs(1, env_extra={"STUB_SLEEP_MS": "150"})
    front = FleetFront(_fast_cfg(tmp_path, specs, threads=3))
    h0 = HEALTH.snapshot()
    try:
        responses = _run(front, _requests(16))
        # the dying replica restarts on the supervisor's cadence, not the
        # workload's: poll briefly for the respawn
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if sum(r.restarts for r in front.supervisor.replicas) >= 1:
                break
            time.sleep(0.05)
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    ids = [r["id"] for r in responses]
    assert len(ids) == len(set(ids)) == 16
    for r in responses:
        _assert_valid(r, 6)
    h = HEALTH.delta_since(h0)
    assert stats["fleet"]["restarts_total"] >= 1
    assert h["fleet_replica_restarts"] >= 1
    # in-flight work moved off the corpse (die-after-2 with 60ms holds
    # guarantees at least one request was in flight at death)
    assert h["fleet_redispatches"] >= 1
    assert stats["fleet"]["redispatches_total"] == h["fleet_redispatches"]


def test_first_writer_wins_suppresses_duplicate(tmp_path):
    """A hop that times out (slow replica) re-dispatches; the slow
    replica's late answer is suppressed — exactly one response."""
    slow = _stub_specs(1, env_extra={"STUB_SLEEP_MS": "1200"})
    fast = _stub_specs(1)
    front = FleetFront(
        _fast_cfg(
            tmp_path, slow + fast, threads=1, hop_timeout_s=0.3,
            # wedge detection OFF the table: the slow replica must stay
            # alive long enough to deliver its late (suppressed) answer
            supervisor=SupervisorConfig(
                probe_interval_s=0.05, wedge_timeout_s=30.0,
                restart_backoff_base_s=0.05, restart_backoff_max_s=0.2,
            ),
        )
    )
    try:
        responses = _run(front, _requests(2, deadline_ms=8000.0))
        # wait for the slow replica's late answers to surface
        deadline = time.monotonic() + 4.0
        while time.monotonic() < deadline:
            stats = json.loads(front.stats_json())
            if stats["fleet"]["duplicates_suppressed"] >= 1:
                break
            time.sleep(0.05)
    finally:
        front.close()
    assert len(responses) == 2
    for r in responses:
        _assert_valid(r, 6)
    assert stats["fleet"]["duplicates_suppressed"] >= 1
    assert stats["fleet"]["redispatches_total"] >= 1


def test_degraded_no_replicas_answers_greedy(tmp_path):
    """Zero replicas: every request still gets a valid tour, locally,
    with the reason counted — the front never queues unboundedly."""
    front = FleetFront(_fast_cfg(tmp_path, []))
    h0 = HEALTH.snapshot()
    try:
        responses = _run(front, _requests(4))
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    for r in responses:
        _assert_valid(r, 6)
        assert r["degraded"] == "no_replicas" and r["tier"] == "greedy"
    assert stats["fleet"]["degraded_answers"]["no_replicas"] == 4
    assert HEALTH.delta_since(h0)["fleet_degraded_answers"] == 4


def test_degraded_answers_from_shared_cache(tmp_path):
    """A degraded front serves CERTIFIED cross-replica work from the
    shared tier instead of falling back to greedy."""
    import tsp_mpi_reduction_tpu.serve.canonical as canon

    rng = np.random.default_rng(3)
    xy = rng.uniform(0, 100, (6, 2))
    ci = canon.canonicalize(xy)
    seed_tier = TieredSolutionCache(4, str(tmp_path / "shared"))
    tour = np.concatenate([np.arange(6, dtype=np.int32), [0]])
    seed_tier.put(
        ci.key,
        CacheEntry(
            cost=canon.tour_length_np(canon.from_canonical_tour(tour, ci), xy),
            tour=tour, certified_gap=0.0, tier="bnb",
        ),
    )
    front = FleetFront(_fast_cfg(tmp_path, []))
    try:
        responses = _run(
            front, [{"id": "c", "xy": xy.tolist(), "deadline_ms": 500.0}]
        )
    finally:
        front.close()
    (resp,) = responses
    _assert_valid(resp, 6)
    assert resp["cache"] == "hit" and resp["tier"] == "bnb"
    assert resp["certified_gap"] == 0.0 and resp["degraded"] == "no_replicas"


@pytest.mark.chaos
def test_dispatch_retry_capped_by_deadline(tmp_path):
    """front.dispatch raising on EVERY crossing: the bounded retry burns
    attempts (counted as retries), never exceeds the request deadline by
    more than slack, and the request still gets a local answer. (Chaos
    marker: this is the ``front.dispatch`` seam's coverage in the
    every-seam-is-exercised guard — the seam fires in the front, so stub
    replicas exercise it exactly as real ones would.)"""
    from tsp_mpi_reduction_tpu.resilience import faults

    front = FleetFront(_fast_cfg(tmp_path, _stub_specs(1)))
    h0 = HEALTH.snapshot()
    faults.configure("front.dispatch:raise,count=0")
    try:
        t0 = time.monotonic()
        responses = _run(front, _requests(2, deadline_ms=400.0))
        wall = time.monotonic() - t0
    finally:
        faults.clear()
        front.close()
    for r in responses:
        _assert_valid(r, 6)
        assert r["degraded"] in ("dispatch", "deadline")
    h = HEALTH.delta_since(h0)
    assert h["retries"] >= 1  # absorbed front.dispatch faults
    assert h["faults_injected"].get("front.dispatch", 0) >= 2
    assert wall < 5.0  # the 400 ms budgets cannot compound into seconds


def test_wedged_stub_detected_and_redispatched(tmp_path):
    """A replica that silently stops answering (no signals — the stub
    just ignores requests) is wedge-detected by the response-flow rule,
    killed, restarted; its requests land elsewhere exactly once."""
    wedge = _stub_specs(1, env_extra={"STUB_IGNORE_AFTER": "1"})
    healthy = _stub_specs(1)
    front = FleetFront(
        _fast_cfg(
            tmp_path, wedge + healthy, threads=2, hop_timeout_s=0.6,
            supervisor=SupervisorConfig(
                probe_interval_s=0.05, wedge_timeout_s=0.4,
                startup_grace_s=0.2, restart_backoff_base_s=0.05,
                restart_backoff_max_s=0.2, healthy_reset_s=2.0,
            ),
        )
    )
    h0 = HEALTH.snapshot()
    try:
        responses = _run(front, _requests(8, deadline_ms=6000.0))
        # the respawn lands on the supervisor's backoff cadence, not the
        # workload's: poll briefly before reading the stats
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if sum(r.restarts for r in front.supervisor.replicas) >= 1:
                break
            time.sleep(0.05)
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    ids = [r["id"] for r in responses]
    assert len(ids) == len(set(ids)) == 8
    for r in responses:
        _assert_valid(r, 6)
    h = HEALTH.delta_since(h0)
    assert h["stuck_restarts"] >= 1  # the wedge verdict
    assert h["fleet_redispatches"] >= 1
    assert stats["fleet"]["restarts_total"] >= 1


def test_restart_backoff_is_bounded(tmp_path):
    """A crash-looping replica's respawn delays follow the bounded
    exponential curve — the scheduled delay never exceeds the cap."""
    from tsp_mpi_reduction_tpu.fleet.replica import Replica

    spec = _stub_specs(1, env_extra={"STUB_DIE_AFTER": "1"})[0]
    rep = Replica(0, spec, on_response=lambda *a: None)
    cap = 0.25
    from tsp_mpi_reduction_tpu.resilience.retry import RetryPolicy

    policy = RetryPolicy(max_attempts=100, base_delay_s=0.05, max_delay_s=cap, seed=0)
    import random as _random

    delays = []
    for attempt in range(1, 12):
        rep.restart_due_at = None  # fresh death
        t0 = time.monotonic()
        rep.schedule_restart(
            lambda k: policy.delay_s(k, _random.Random(k))
        )
        delays.append(rep.restart_due_at - t0)
    assert all(d <= cap + 0.01 for d in delays)
    assert delays[0] <= 0.06  # first retry is fast
    # the curve actually grew toward the cap before flattening
    assert max(delays) > delays[0]


def test_front_stats_fleet_block_and_obs_report(tmp_path, capsys):
    """The stats line carries the fleet block; obs_report --fleet renders
    it and exits 2 on a payload without one."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import obs_report

    front = FleetFront(_fast_cfg(tmp_path, _stub_specs(1)))
    try:
        _run(front, _requests(3))
        stats_line = front.stats_json()
    finally:
        front.close()
    stats = json.loads(stats_line)
    assert set(stats["fleet"]) >= {
        "replicas", "replica_count", "alive", "restarts_total",
        "redispatches_total", "degraded_answers", "duplicates_suppressed",
        "shared_cache",
    }
    good = tmp_path / "fleet_stats.json"
    good.write_text(stats_line + "\n")
    assert obs_report.main(["--fleet", str(good)]) == 0
    out = capsys.readouterr().out
    assert "replica 0" in out and "supervision:" in out
    # a plain serve stats payload (no fleet block) is exit 2
    bad = tmp_path / "serve_stats.json"
    bad.write_text(json.dumps({"responses": 1, "cache": {}}) + "\n")
    assert obs_report.main(["--fleet", str(bad)]) == 2


def test_fleet_stats_slo_block_judges_front_latency(tmp_path):
    """The front's fleet-level SLO verdicts come from its OWN end-to-end
    histograms (fleet_request_seconds), session-windowed."""
    front = FleetFront(_fast_cfg(tmp_path, _stub_specs(1)))
    try:
        _run(front, _requests(5, deadline_ms=5000.0))
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    greedy = stats["slo"]["greedy"]
    assert greedy["requests"] == 5
    assert greedy["attainment"] is not None

"""LRU solution cache (serve.cache): eviction order, counters, policy."""

import threading

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.serve.cache import CacheEntry, SolutionCache

pytestmark = pytest.mark.serve


def _entry(cost, gap=0.0, tier="pipeline"):
    return CacheEntry(
        cost=cost, tour=np.asarray([0, 1, 2, 0], np.int32),
        certified_gap=gap, tier=tier,
    )


def test_hit_miss_counters():
    c = SolutionCache(capacity=4)
    assert c.get("a") is None
    c.put("a", _entry(1.0))
    assert c.get("a") is not None
    s = c.stats()
    assert (s["hits"], s["misses"], s["evictions"], s["size"]) == (1, 1, 0, 1)


def test_lru_eviction_order():
    c = SolutionCache(capacity=2)
    c.put("a", _entry(1.0))
    c.put("b", _entry(2.0))
    assert c.get("a") is not None  # refresh a: b is now coldest
    c.put("c", _entry(3.0))
    assert c.get("b") is None, "coldest entry should have been evicted"
    assert c.get("a") is not None and c.get("c") is not None
    assert c.stats()["evictions"] == 1


def test_put_keeps_better_entry():
    c = SolutionCache(capacity=4)
    c.put("k", _entry(10.0, gap=0.0, tier="bnb"))
    # a later, WORSE answer (deadline-degraded greedy) must not clobber it
    c.put("k", _entry(12.0, gap=None, tier="greedy"))
    assert c.get("k").tier == "bnb"
    # a strictly cheaper tour replaces
    c.put("k", _entry(9.0, gap=None, tier="pipeline"))
    assert c.get("k").cost == 9.0
    # equal cost: a certificate beats none
    c.put("k", _entry(9.0, gap=0.0, tier="bnb"))
    assert c.get("k").certified_gap == 0.0


def test_capacity_validation():
    with pytest.raises(ValueError):
        SolutionCache(capacity=0)


def test_concurrent_access_consistent():
    c = SolutionCache(capacity=64)
    errs = []

    def worker(seed):
        try:
            rng = np.random.default_rng(seed)
            for _ in range(300):
                k = f"k{int(rng.integers(0, 100))}"
                if rng.random() < 0.5:
                    c.put(k, _entry(float(rng.random())))
                else:
                    c.get(k)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    s = c.stats()
    assert s["size"] <= 64
    # every get either hit or missed — 8 threads x 300 ops, ~half gets
    assert s["hits"] + s["misses"] + s["evictions"] > 0

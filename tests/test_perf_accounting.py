"""Performance-accounting layer tests (ISSUE 9).

Covers the four tentpole pieces and their seams:

- ``obs.costs``: XLA cost capture golden schema, roofline math, the
  compile-cache custody wiring (miss/hit/unsupported paths), and the
  on-disk memo that keeps warm processes honest.
- ``obs.bench_history`` + ``tools/bench_check.py``: record schema,
  locked concurrent appends, the median+MAD regression detector (clean
  trend passes, an injected 20% slowdown FAILS under the default rules,
  below min-samples is tolerated), CLI exit codes.
- cross-process tracing: the ``TSP_TRACE_PARENT`` env contract, and a
  real 2-chunk ``bnb_chunked`` campaign reconstructing as ONE span tree
  with zero orphans.
- ``obs.slo`` + ``obs.anomaly``: histogram attainment interpolation,
  burn-rate math, stats-JSON integration, and the stall sentinel's
  fire-once-per-episode behavior feeding health events.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.obs import anomaly, bench_history as bh, costs, slo, tracing
from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _reset_costs():
    costs.reset_for_testing()
    yield
    costs.reset_for_testing()


# -- obs.costs -----------------------------------------------------------------

#: every captured entry must carry these (the obs.device_costs golden
#: schema — bnb_solve payload, serve stats, and BENCH artifacts all
#: stamp this exact record shape)
DEVICE_COST_ENTRY_SCHEMA = {
    "schema": int, "backend": str, "flops": float, "bytes_accessed": float,
    "arithmetic_intensity": float, "ridge_intensity": float,
    "roofline_utilization_est": float, "bound": str,
    "peak_flops_per_s": float, "peak_bytes_per_s": float,
}


def _compiled_toy(shape=(32, 32)):
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: jnp.sin(x) @ x)
    return f, (jnp.ones(shape, jnp.float32),)


def test_capture_golden_schema_and_roofline():
    import jax

    f, args = _compiled_toy()
    compiled = f.lower(*args).compile()
    rec = costs.capture("toy_entry", compiled, backend="cpu")
    assert rec is not None
    for key, typ in DEVICE_COST_ENTRY_SCHEMA.items():
        assert key in rec, key
        assert isinstance(rec[key], typ), (key, type(rec[key]))
    assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
    # roofline identity: utilization = min(peak, I*bw)/peak
    peaks = costs.backend_peaks("cpu")
    intensity = rec["flops"] / rec["bytes_accessed"]
    want = min(peaks["flops_per_s"], intensity * peaks["bytes_per_s"]) / peaks["flops_per_s"]
    assert rec["roofline_utilization_est"] == pytest.approx(want, rel=1e-3)
    assert rec["bound"] in ("memory", "compute")
    # memory_analysis fields ride along on jax 0.4.x
    assert rec["peak_memory_bytes"] > 0
    # mirrored as entry-labeled gauges
    assert REGISTRY.value("xla_entry_flops", entry="toy_entry") == rec["flops"]
    # the block lists the entry + the peak table it was judged against
    block = costs.device_costs_block()
    assert "toy_entry" in block["entries"]
    assert "cpu" in block["peaks"]
    del jax


def test_capture_failure_counts_never_raises():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no analysis on this backend")

    before = REGISTRY.value("xla_cost_capture_failures_total", entry="broken")
    assert costs.capture("broken", Broken(), backend="cpu") is None
    after = REGISTRY.value("xla_cost_capture_failures_total", entry="broken")
    assert after == before + 1
    assert costs.get("broken") is None


def test_roofline_bound_classification_and_peak_override(monkeypatch):
    # intensity above the ridge -> compute-bound
    rec = costs.ingest("hot", {
        "schema": costs.SCHEMA_VERSION, "backend": "cpu",
        "flops": 1e9, "bytes_accessed": 1e3,
    })
    assert rec["bound"] == "compute"
    assert rec["roofline_utilization_est"] == 1.0
    # env override reshapes the roofline
    monkeypatch.setenv("TSP_PEAK_FLOPS", "2.0e12")
    assert costs.backend_peaks("cpu")["flops_per_s"] == 2.0e12
    monkeypatch.setenv("TSP_PEAK_FLOPS", "not-a-number")
    assert costs.backend_peaks("cpu")["flops_per_s"] == \
        costs.BACKEND_PEAKS["cpu"]["flops_per_s"]


def test_aot_store_captures_and_memoizes_costs(tmp_path, monkeypatch):
    """The compile-cache custody wiring: a miss captures live; a fresh
    'process' (cleared in-memory store) re-holds the record on the hit
    path; an unsupported-marked entry rehydrates from the DISK memo —
    the warm-chunk path XLA:CPU forces on the real hot entries."""
    from tsp_mpi_reduction_tpu.perf import compile_cache as cc

    monkeypatch.setenv("TSP_COMPILE_CACHE", str(tmp_path / "cache"))
    monkeypatch.setattr(cc, "_enabled_dir", None)
    cc.enable()
    assert cc.enabled_dir() is not None

    f, args = _compiled_toy((16, 16))
    assert cc.aot_load_or_compile("memo_entry", f, args) is not None
    rec = costs.get("memo_entry")
    assert rec is not None and rec["flops"] > 0

    # warm hit path: in-memory cost store cleared (the executable memo
    # keeps the Compiled) — the record must come back on the hit
    costs.reset_for_testing()
    assert cc.aot_load_or_compile("memo_entry", f, args) is not None
    assert costs.get("memo_entry") is not None

    # unsupported path: mark the entry and simulate a FRESH process
    # (cost store AND executable memo cleared) — the disk memo is now
    # the ONLY source and must rehydrate
    key = cc.entry_key("memo_entry", args, {})
    _exec, _meta, unsupported = cc._aot_paths(key)
    cc._atomic_write(unsupported, b"")
    costs.reset_for_testing()
    cc._AOT_LOADED.clear()
    assert cc.aot_load_or_compile("memo_entry", f, args) is None
    rec2 = costs.get("memo_entry")
    assert rec2 is not None and rec2["flops"] == rec["flops"]


def test_obs_block_carries_device_costs():
    from tsp_mpi_reduction_tpu.utils import reporting

    costs.ingest("entry_a", {
        "schema": costs.SCHEMA_VERSION, "backend": "cpu",
        "flops": 10.0, "bytes_accessed": 5.0,
    })
    block = reporting.obs_block(trace_path=None)
    assert block["device_costs"]["entries"]["entry_a"]["flops"] == 10.0
    json.dumps(block)  # stats-JSON encodable


# -- obs.bench_history ---------------------------------------------------------

#: golden schema of one history line (tools/bench_check.py and the docs
#: both promise this shape)
HISTORY_RECORD_SCHEMA = {
    "schema": int, "ts": float, "mode": str, "metric": str,
    "backend": str, "host": str, "config": dict, "config_hash": str,
}


def test_history_record_schema_and_roundtrip(tmp_path):
    path = str(tmp_path / "h.jsonl")
    rec = bh.make_record(
        "bnb", {"metric": "bnb_eil51_nodes_per_sec", "value": 123.4,
                "unit": "nodes/s", "ok": True},
        config={"k": 1024}, backend="cpu",
    )
    for key, typ in HISTORY_RECORD_SCHEMA.items():
        assert key in rec, key
        assert isinstance(rec[key], typ), (key, type(rec[key]))
    assert rec["value"] == 123.4 and rec["unit"] == "nodes/s"
    # git rev present in this checkout (None tolerated elsewhere)
    assert rec["git_rev"]
    bh.append(path, rec)
    bh.append(path, rec)
    back = bh.read(path)
    assert len(back) == 2 and back[0]["metric"] == "bnb_eil51_nodes_per_sec"
    # torn tail is skipped, surviving lines still parse
    with open(path, "a") as fh:
        fh.write('{"metric": "torn')
    assert len(bh.read(path)) == 2


def test_history_config_hash_separates_configs():
    a = bh.config_hash({"k": 1024})
    assert a == bh.config_hash({"k": 1024})
    assert a != bh.config_hash({"k": 256})


def test_history_concurrent_appends_interleave_whole_lines(tmp_path):
    path = str(tmp_path / "h.jsonl")
    n_threads, per_thread = 8, 25

    def writer(i):
        for j in range(per_thread):
            bh.append(path, {"metric": "m", "value": i * 1000 + j})

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = bh.read(path)
    assert len(recs) == n_threads * per_thread
    assert {r["value"] for r in recs} == {
        i * 1000 + j for i in range(n_threads) for j in range(per_thread)
    }


def _mk_history(metric, values, backend="cpu", config=None):
    return [
        bh.make_record("x", {"metric": metric, "value": v},
                       config=config or {}, backend=backend)
        for v in values
    ]


def test_detector_clean_trend_passes():
    recs = _mk_history("bnb_eil51_nodes_per_sec",
                       [16000, 16400, 15900, 16200, 16100, 16300])
    (v,) = bh.check(recs)
    assert v.status == "ok"


def test_detector_fails_20pct_throughput_regression():
    """The acceptance bar: a synthetic 20% slowdown on a throughput
    metric FAILS under the DEFAULT rules."""
    base = [16000, 16400, 15900, 16200, 16100, 16300]
    recs = _mk_history("bnb_eil51_nodes_per_sec", base + [16100 * 0.8])
    (v,) = bh.check(recs)
    assert v.status == "regression", v.detail
    # and the wall-clock direction: 20% SLOWER pipeline fails too
    recs = _mk_history("pipeline_16x100_wall_ms",
                       [100, 101, 99, 100, 102, 100 * 1.2])
    (v,) = bh.check(recs)
    assert v.status == "regression", v.detail


def test_detector_direction_asymmetry():
    # a throughput IMPROVEMENT never fails
    base = [16000, 16400, 15900, 16200, 16100]
    recs = _mk_history("bnb_eil51_nodes_per_sec", base + [16100 * 1.5])
    (v,) = bh.check(recs)
    assert v.status == "ok"


def test_detector_tolerant_below_min_samples():
    recs = _mk_history("bnb_eil51_nodes_per_sec", [16000, 9000])
    (v,) = bh.check(recs)
    assert v.status == "insufficient"


def test_detector_mad_floor_absorbs_noisy_history():
    """A metric whose own history wobbles hard gets a wider band: the
    newest sample sits ~27% over the median (past the 15% explicit
    band), but the history's MAD already brackets swings that size."""
    noisy = [100, 140, 80, 130, 75, 135, 85, 120]  # median 110, MAD 25
    recs = _mk_history("pipeline_16x100_wall_ms", noisy + [140])
    (v,) = bh.check(recs)
    assert v.status == "ok", v.detail


def test_detector_groups_by_backend_and_config():
    cpu = _mk_history("bnb_eil51_nodes_per_sec",
                      [16000, 16100, 15900, 16050, 16000], backend="cpu")
    # a TPU group with 10x the rate must not drag the CPU median
    tpu = _mk_history("bnb_eil51_nodes_per_sec",
                      [160000, 161000, 159000, 160500, 160000], backend="tpu")
    verdicts = bh.check(cpu + tpu)
    assert len(verdicts) == 2
    assert all(v.status == "ok" for v in verdicts)


def test_detector_groups_by_host_fingerprint():
    """A fresh clone on DIFFERENT hardware must start its own history:
    its first (slower) sample lands in a new (.., host) group and reads
    `insufficient`, never `regression` against the shipped machine's
    medians — the default `make` chains bench-check, so grouping a slow
    laptop with the author's host would fail every fresh checkout."""
    fast = _mk_history("bnb_eil51_nodes_per_sec",
                       [16000, 16100, 15900, 16050, 16000])
    slow = bh.make_record("x", {"metric": "bnb_eil51_nodes_per_sec",
                                "value": 4000.0}, config={}, backend="cpu")
    slow["host"] = "aaaaaaaaaaaa"  # some other machine
    verdicts = bh.check(fast + [slow])
    assert len(verdicts) == 2
    by_host = {v.group.rsplit("/", 1)[-1]: v for v in verdicts}
    assert by_host["aaaaaaaaaaaa"].status == "insufficient"
    assert by_host[bh.host_fingerprint()].status == "ok"


def test_bench_check_append_honors_history_off(tmp_path, monkeypatch):
    """TSP_BENCH_HISTORY=off is the WRITE kill switch: the append
    subcommand must skip (exit 0) instead of falling back to the
    checked-in repo file."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_check
    finally:
        sys.path.pop(0)
    art = tmp_path / "BENCH_X.json"
    art.write_text(json.dumps({"metric": "m", "value": 1.0}))
    monkeypatch.setenv(bh.ENV_VAR, "off")
    before = (REPO / bh.DEFAULT_PATH).read_text()
    assert bench_check.main(["append", str(art), "--mode", "x"]) == 0
    assert (REPO / bh.DEFAULT_PATH).read_text() == before
    # an EXPLICIT --history overrides the kill switch (operator intent)
    dest = tmp_path / "h.jsonl"
    assert bench_check.main(
        ["append", str(art), "--mode", "x", "--history", str(dest)]
    ) == 0
    assert len(bh.read(str(dest))) == 1


def test_load_rules_merges_over_defaults(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({
        "bnb_eil51_nodes_per_sec": {"direction": "higher", "rel_threshold": 0.5},
        "obs_overhead": None,
        "my_metric": {"direction": "lower", "min_samples": 2},
    }))
    rules = bh.load_rules(str(p))
    assert rules["bnb_eil51_nodes_per_sec"].rel_threshold == 0.5
    assert "obs_overhead" not in rules
    assert rules["my_metric"].min_samples == 2
    assert "pipeline_16x100_wall_ms" in rules  # defaults survive


def test_bench_check_cli_gates_and_appends(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_check
    finally:
        sys.path.pop(0)
    hist = str(tmp_path / "h.jsonl")
    # empty history: pass (nothing to gate)
    assert bench_check.main(["check", "--history", hist]) == 0
    # append subcommand from a BENCH artifact
    art = tmp_path / "BENCH_X.json"
    art.write_text(json.dumps(
        {"metric": "bnb_eil51_nodes_per_sec", "value": 16000.0}
    ))
    for _ in range(6):
        assert bench_check.main(
            ["append", str(art), "--mode", "bnb", "--history", hist,
             "--backend", "cpu"]
        ) == 0
    assert bench_check.main(["check", "--history", hist]) == 0
    # a 25% regression in the newest sample fails the gate
    art.write_text(json.dumps(
        {"metric": "bnb_eil51_nodes_per_sec", "value": 12000.0}
    ))
    assert bench_check.main(
        ["append", str(art), "--mode", "bnb", "--history", hist,
         "--backend", "cpu"]
    ) == 0
    assert bench_check.main(["check", "--history", hist]) == 1
    # --json verdict payload carries the failure
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_check.main(["check", "--history", hist, "--json"])
    assert rc == 1
    doc = json.loads(buf.getvalue())
    assert doc["ok"] is False and doc["regressions"] == 1
    # artifact without a metric headline is refused
    bad = tmp_path / "notbench.json"
    bad.write_text(json.dumps({"hello": 1}))
    assert bench_check.main(
        ["append", str(bad), "--mode", "x", "--history", hist]
    ) == 2


def test_repo_history_file_passes_the_gate():
    """`make bench-check` must pass on the repo's REAL checked-in
    history (the acceptance criterion) — run the same entry point."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_check
    finally:
        sys.path.pop(0)
    hist = REPO / bh.DEFAULT_PATH
    assert bench_check.main(["check", "--history", str(hist)]) == 0


# -- cross-process trace propagation -------------------------------------------


def test_trace_parent_env_contract(monkeypatch):
    assert tracing.format_parent(None) is None
    assert tracing.format_parent(("ab12", "cd34")) == "ab12:cd34"
    monkeypatch.setenv(tracing.ENV_PARENT, "ab12:cd34")
    assert tracing.parent_from_env() == ("ab12", "cd34")
    # normalization is tolerant: case + surrounding whitespace
    monkeypatch.setenv(tracing.ENV_PARENT, " AB12:CD34 ")
    assert tracing.parent_from_env() == ("ab12", "cd34")
    for bad in ("", "no-colon", ":", "xyz:!!", "ab12:", ":cd34"):
        monkeypatch.setenv(tracing.ENV_PARENT, bad)
        assert tracing.parent_from_env() is None, bad
    monkeypatch.delenv(tracing.ENV_PARENT)
    assert tracing.parent_from_env() is None


def test_span_under_env_parent_joins_the_trace(tmp_path, monkeypatch):
    sink = str(tmp_path / "t.jsonl")
    tracing.configure(sink)
    try:
        with tracing.span("parent.root") as root:
            ctx = root.context
        monkeypatch.setenv(tracing.ENV_PARENT, tracing.format_parent(ctx))
        with tracing.span("child.solve", parent=tracing.parent_from_env()):
            pass
    finally:
        tracing.configure(None)
    spans = tracing.read_trace(sink)
    trees = tracing.build_trees(spans)
    assert len(trees) == 1
    assert not tracing.orphan_spans(spans)
    (tree,) = trees.values()
    (root_node,) = tree["roots"]
    assert root_node["span"]["name"] == "parent.root"
    assert root_node["children"][0]["span"]["name"] == "child.solve"


def test_read_traces_stitches_files(tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    tracing.configure(a)
    try:
        with tracing.span("root") as root:
            ctx = root.context
    finally:
        tracing.configure(None)
    tracing.configure(b)
    try:
        with tracing.span("leaf", parent=ctx):
            pass
    finally:
        tracing.configure(None)
    # each file alone: the leaf's parent is missing -> orphan
    assert len(tracing.orphan_spans(tracing.read_trace(b))) == 1
    # stitched: one complete tree (missing files are skipped, not fatal)
    spans = tracing.read_traces([a, b, str(tmp_path / "missing.jsonl")])
    assert len(spans) == 2
    assert not tracing.orphan_spans(spans)


def test_two_chunk_campaign_single_span_tree(tmp_path):
    """Acceptance: a 2-chunk bnb_chunked campaign under TSP_TRACE +
    TSP_TRACE_PARENT reconstructs as a SINGLE span tree, 0 orphans —
    campaign root -> per-chunk spans -> each chunk subprocess's
    bnb.solve root (its compile/aot_load phases underneath)."""
    tool = str(REPO / "tools" / "bnb_chunked.py")
    sink = str(tmp_path / "campaign.jsonl")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TSP_TRACE=sink,
        TSP_COMPILE_CACHE=str(tmp_path / "cc"),
        TSP_BENCH_HISTORY="off",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, tool, "burma14", "--chunk-iters=40", "--max-chunks=2",
         f"--checkpoint={tmp_path}/c.npz", "--k=16", "--capacity=8192",
         "--bound=min-out", "--node-ascent=0"],
        capture_output=True, text=True, env=env, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    chunk_lines = [json.loads(x) for x in r.stdout.strip().splitlines()]
    summary = chunk_lines[-1]
    assert summary["chunks"] == 2, "config no longer needs 2 chunks"

    spans = tracing.read_trace(sink)
    trees = tracing.build_trees(spans)
    assert len(trees) == 1, f"expected ONE trace, got {len(trees)}"
    assert tracing.orphan_spans(spans) == []
    (tree,) = trees.values()
    (root,) = tree["roots"]
    assert root["span"]["name"] == "bnb.campaign"
    chunk_nodes = [
        c for c in root["children"] if c["span"]["name"] == "campaign.chunk"
    ]
    assert len(chunk_nodes) == 2
    for node in chunk_nodes:
        names = [c["span"]["name"] for c in node["children"]]
        assert "bnb.solve" in names
    # chunk 1 paid the compile; its solve span shows the phase
    all_names = {s["name"] for s in spans}
    assert "compile" in all_names or "aot_load" in all_names


# -- obs.slo -------------------------------------------------------------------


def _hist(buckets, counts, total=None):
    count = sum(counts)
    return {"buckets": list(buckets), "counts": list(counts),
            "sum": 0.0, "count": total if total is not None else count}


def test_hist_attainment_exact_edges_and_interpolation():
    h = _hist([0.1, 0.5, 1.0], [10, 10, 10, 10])  # +Inf bucket holds 10
    assert slo.hist_attainment(h, 0.1) == pytest.approx(0.25)
    assert slo.hist_attainment(h, 1.0) == pytest.approx(0.75)
    # halfway through the (0.1, 0.5] bucket: 10 + 5 of 40
    assert slo.hist_attainment(h, 0.3) == pytest.approx(0.375)
    # beyond the last finite edge: +Inf observations never attain
    assert slo.hist_attainment(h, 5.0) == pytest.approx(0.75)
    assert slo.hist_attainment({"buckets": [], "counts": [], "count": 0},
                               1.0) is None


def test_slo_evaluate_attainment_burn_and_unjudged_tiers():
    hists = {
        # 98 of 100 inside 50 ms against a 99% goal -> burn 2.0
        "greedy": _hist([0.05, 0.5], [98, 2, 0]),
        # traffic on a tier with no objective
        "mystery": _hist([0.05], [3, 0]),
    }
    out = slo.evaluate(hists, {
        "greedy": {"target_ms": 50.0, "goal": 0.99},
        "bnb": {"target_ms": 1000.0, "goal": 0.95},
    })
    g = out["greedy"]
    assert g["attainment"] == pytest.approx(0.98)
    assert g["burn_rate"] == pytest.approx(2.0)
    assert g["ok"] is False
    # objective with no traffic: present, unjudged
    assert out["bnb"]["requests"] == 0 and out["bnb"]["ok"] is None
    # traffic with no objective: listed, explicitly unjudged
    assert out["mystery"]["objective"] is None


@pytest.mark.serve
def test_service_stats_slo_block_reflects_session_traffic():
    import io

    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    rng = np.random.default_rng(11)
    lines = [
        json.dumps({"id": f"r{i}", "xy": (rng.random((8, 2)) * 50).tolist(),
                    "deadline_ms": 2500.0})
        for i in range(5)
    ]
    out = io.StringIO()
    svc = run_jsonl(lines, out, ServiceConfig(threads=2, max_wait_ms=1.0))
    stats = json.loads(svc.stats_json())
    slo_block = stats["slo"]
    # every responding tier is judged; total judged requests == responses
    judged = sum(row.get("requests", 0) for row in slo_block.values())
    assert judged == 5
    for tier, row in slo_block.items():
        if row.get("requests", 0) and row.get("attainment") is not None:
            assert 0.0 <= row["attainment"] <= 1.0
            assert row["burn_rate"] >= 0.0
    # a SECOND service in the same process starts a fresh SLO window
    svc2 = run_jsonl(lines[:2], io.StringIO(),
                     ServiceConfig(threads=2, max_wait_ms=1.0))
    stats2 = json.loads(svc2.stats_json())
    assert sum(r.get("requests", 0) for r in stats2["slo"].values()) == 2


# -- obs.anomaly ---------------------------------------------------------------


def test_sentinel_rate_collapse_fires_once_per_episode():
    s = anomaly.StallSentinel(window=4, lb_window=1000)
    fired = []
    for i in range(16):
        fired += s.observe(step=i, nodes_per_s=1000.0, lb_floor=float(i))
    assert fired == []
    for i in range(16, 48):  # collapsed stretch: ONE event
        fired += s.observe(step=i, nodes_per_s=10.0, lb_floor=float(i))
    kinds = [e["kind"] for e in fired]
    assert kinds == ["nodes_rate_collapse"]
    # recovery re-arms; a second collapse fires again
    for i in range(48, 96):
        fired += s.observe(step=i, nodes_per_s=1000.0, lb_floor=float(i))
    for i in range(96, 128):
        fired += s.observe(step=i, nodes_per_s=10.0, lb_floor=float(i))
    assert [e["kind"] for e in fired].count("nodes_rate_collapse") == 2


def test_sentinel_lb_stagnation_needs_both_flat():
    # flat floor + improving incumbent: NORMAL mid-DFS, no alarm
    s = anomaly.StallSentinel(window=4, lb_window=16)
    fired = []
    for i in range(64):
        fired += s.observe(step=i, nodes_per_s=100.0, lb_floor=42.0,
                           incumbent=1000.0 - i)
    assert fired == []
    # flat floor + flat incumbent (open work not draining): total
    # stagnation, ONE event
    s2 = anomaly.StallSentinel(window=4, lb_window=16)
    fired2 = []
    for i in range(64):
        fired2 += s2.observe(step=i, nodes_per_s=100.0, lb_floor=42.0,
                             incumbent=500.0, open_nodes=1000 + i)
    assert [e["kind"] for e in fired2] == ["lb_stagnation"]
    assert s2.summary()["fired"] == 1


def test_sentinel_lb_stagnation_spares_draining_proof_phase():
    """Flat floor + flat incumbent is the NORMAL prove-the-incumbent
    endgame whenever the open set is draining — within one solve the
    certified floor cannot move (clamped once at setup) and the optimal
    incumbent never improves, so without the drain condition the
    detector fired on every healthy proof run longer than lb_window
    dispatches (reproduced on the TSP_BENCH=obs config)."""
    s = anomaly.StallSentinel(window=4, lb_window=16)
    fired = []
    for i in range(200):
        fired += s.observe(step=i, nodes_per_s=100.0, lb_floor=42.0,
                           incumbent=500.0, open_nodes=2000 - 10 * i)
    assert fired == []


def test_healthy_proof_run_fires_no_anomalies():
    """End-to-end guard for the same false positive: the TSP_BENCH=obs
    acceptance config — a healthy run that finds the optimum early and
    spends >lb_window dispatches proving it — must report zero events."""
    from tsp_mpi_reduction_tpu import obs as _obs
    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.resolve_instance("random:12:33")
    d = np.rint(inst.distance_matrix() * 10)
    _obs.set_enabled(True)
    try:
        res = bb.solve(d, capacity=2048, k=8, inner_steps=4,
                       bound="min-out", mst_prune=False, node_ascent=0,
                       device_loop=False)
    finally:
        _obs.set_enabled(None)
    assert res.proven_optimal
    assert res.series["samples_total"] > 256  # long enough to have fired
    assert res.anomalies == {"events": [], "fired": 0}


def test_sentinel_fires_health_events_and_registry_counters():
    from tsp_mpi_reduction_tpu.resilience.health import HEALTH

    before = REGISTRY.value("bnb_anomalies_total", kind="lb_stagnation")
    s = anomaly.StallSentinel(window=4, lb_window=8)
    for i in range(32):
        s.observe(step=i, nodes_per_s=100.0, lb_floor=1.0, incumbent=2.0)
    assert HEALTH.get("anomaly_lb_stagnation") >= 1
    assert REGISTRY.value("bnb_anomalies_total", kind="lb_stagnation") > before


def test_sentinel_maybe_respects_tsp_obs():
    from tsp_mpi_reduction_tpu import obs

    obs.set_enabled(False)
    try:
        assert anomaly.StallSentinel.maybe() is None
    finally:
        obs.set_enabled(None)
    assert anomaly.StallSentinel.maybe() is not None


def test_solve_payload_carries_anomalies_block():
    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.resolve_instance("random:9:5")
    res = bb.solve(inst.distance_matrix(), capacity=256, k=8, inner_steps=4,
                   bound="min-out", mst_prune=False, node_ascent=0,
                   device_loop=False)
    assert res.anomalies is not None
    assert set(res.anomalies) == {"events", "fired"}
    assert res.anomalies["fired"] == len(res.anomalies["events"])
    json.dumps(res.anomalies)


def test_obs_report_missing_trace_path_errors(tmp_path):
    """A typo'd / never-created --trace sink must exit 2 with an error,
    not render a healthy-looking '0 spans, 0 orphans' (read_traces'
    skip-unreadable lenience is for programmatic stitching only)."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    missing = str(tmp_path / "never_written.jsonl")
    assert obs_report.main(["--trace", missing]) == 2


# -- metrics HTTP lifecycle ----------------------------------------------------


def test_metrics_http_port0_binds_and_close_releases():
    import socket
    import urllib.request

    from tsp_mpi_reduction_tpu.obs.metrics import serve_metrics_http

    server = serve_metrics_http(0)
    port = server.port
    assert port > 0
    REGISTRY.inc("http_lifecycle_probe_total")
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    assert "http_lifecycle_probe_total" in body
    server.close()
    # the socket is RELEASED, not just the loop stopped: rebinding the
    # exact port succeeds immediately (multi-instance / test reruns)
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        probe.bind(("127.0.0.1", port))
    finally:
        probe.close()

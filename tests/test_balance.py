"""Adaptive load balance (ISSUE 15): assignment math, multiset safety,
controller policy, zero-dispatch regimes, no-retrace across solves.

Layered like the module under test: the pure assignment plans are fuzzed
mesh-free (conservation/partition are properties of the math alone), the
shard-local collective steps are property-tested on a real 4-rank mesh
with unique row payloads (the global multiset of live rows must survive
ANY action under ANY skew), and the controller's policy (dead-band, worth
floor, escalation, hysteresis, forced skip) is pinned host-side before
the end-to-end solve tests exercise the whole closed loop.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tsp_mpi_reduction_tpu.analysis.contracts import RecompilationGuard
from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.parallel import balance as bal
from tsp_mpi_reduction_tpu.parallel.mesh import RANK_AXIS, make_rank_mesh
from tsp_mpi_reduction_tpu.utils.backend import shard_map


def random_d(n, seed):
    xy = np.random.default_rng(seed).uniform(0, 100, (n, 2))
    return np.rint(np.sqrt(((xy[:, None] - xy[None]) ** 2).sum(-1)) * 10)


def symmetric_d(n_ring=12):
    """Vertex-transitive ring + center city: every rank's root subtrees
    are equivalent under round-robin dealing, so occupancy STAYS balanced
    for the whole solve — the only honest zero-dispatch control (a random
    instance de-balances structurally mid-solve no matter how the roots
    are dealt)."""
    th = np.linspace(0, 2 * np.pi, n_ring, endpoint=False)
    xy = np.concatenate(
        [np.stack([50 + 40 * np.cos(th), 50 + 40 * np.sin(th)], 1),
         [[50.0, 50.0]]]
    )
    return np.rint(np.sqrt(((xy[:, None] - xy[None]) ** 2).sum(-1)) * 10)


# -- pure assignment math ------------------------------------------------------


def test_steal_assignment_partitions_the_pool():
    """Donor and receiver intervals must each partition [0, moved) exactly
    — conservation by construction, robust to zero-width donors — and the
    plan must never overfill a receiver past the mean."""
    rng = np.random.default_rng(7)
    cases = [
        np.array([200, 0, 0, 0]),            # total starvation
        np.array([100, 50, 50, 0]),          # zero-width middle donors
        np.array([60, 60, 60, 60]),          # balanced: nothing moves
        np.array([0, 0, 0, 0]),              # drained
        np.array([1, 0, 0, 0]),              # sub-slab surplus
        np.array([5, 200, 7, 200, 0, 3, 0, 190]),  # 8 ranks, mixed
    ]
    for _ in range(40):
        r = int(rng.integers(2, 9))
        cases.append(rng.integers(0, 240, r))
    for counts in cases:
        counts = counts.astype(np.int32)
        cap = 256
        for t_slots in (1, 4, 16, 64):
            m_out, m_in, pool_off, take_off = (
                np.asarray(x, np.int64)
                for x in bal.steal_assignment(jnp.asarray(counts), t_slots)
            )
            moved = m_out.sum()
            assert moved == m_in.sum()  # conservation
            assert (m_out >= 0).all() and (m_out <= t_slots).all()
            assert (m_in >= 0).all() and (m_in <= t_slots).all()
            # no rank is both donor and receiver
            assert (m_out * m_in == 0).all()
            # donor/receiver intervals each partition [0, moved)
            for off, width in ((pool_off, m_out), (take_off, m_in)):
                lanes = [
                    p
                    for o, w in zip(off, width)
                    for p in range(int(o), int(o + w))
                ]
                assert sorted(lanes) == list(range(int(moved)))
            # post-plan occupancy stays within [0, capacity]
            after = counts - m_out + m_in
            assert (after >= 0).all() and (after <= cap).all()
            mean = counts.sum() // len(counts)
            assert (after[m_in > 0] <= mean).all()
            assert (after[m_out > 0] >= mean).all()


def _run_action(action, mesh, nodes, counts, round_i, *, t_slots, capacity,
                phys_rows):
    """One balance collective on a real mesh, via the same apply() the
    solver's per-action shard_map bodies call."""
    num_ranks = mesh.devices.size
    perm_fwd = [(r, (r + 1) % num_ranks) for r in range(num_ranks)]
    perm_back = [((r + 1) % num_ranks, r) for r in range(num_ranks)]

    def body(nd, c, r):
        nd2, c2, m = bal.apply(
            action, nd[0], c[0], r, num_ranks=num_ranks, t_slots=t_slots,
            capacity=capacity, phys_rows=phys_rows, perm_fwd=perm_fwd,
            perm_back=perm_back,
        )
        return nd2[None], c2[None], m[None]

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(RANK_AXIS), P(RANK_AXIS), P()),
        out_specs=(P(RANK_AXIS), P(RANK_AXIS), P(RANK_AXIS)),
    ))
    return fn(nodes, counts, round_i)


@pytest.mark.slow
@pytest.mark.parametrize("action", bal.ACTIONS)
def test_actions_preserve_live_row_multiset(action):
    """The satellite's core safety property: EVERY balance action, under
    ANY skew pattern, preserves the global multiset of live rows — no row
    duplicated, dropped, or invented — and never overfills a receiver."""
    R, capacity, t_slots, cols = 4, 32, 8, 5
    phys_rows = capacity + 4  # dead receive lanes park at phys_rows
    skews = [
        [32, 0, 0, 0],
        [32, 28, 1, 0],
        [8, 8, 8, 8],
        [0, 0, 0, 0],
        [1, 0, 31, 0],
        [32, 32, 32, 32],
    ]
    rng = np.random.default_rng(11)
    for _ in range(6):
        skews.append(rng.integers(0, capacity + 1, R).tolist())
    for counts in skews:
        counts = np.asarray(counts, np.int32)
        # unique payload per cell; dead/padding rows carry a sentinel
        nodes = np.full((R, phys_rows, cols), -7, np.int32)
        payload = np.arange(R * phys_rows * cols, dtype=np.int32).reshape(
            R, phys_rows, cols
        )
        for r in range(R):
            nodes[r, : counts[r]] = payload[r, : counts[r]]
        before = sorted(
            tuple(row)
            for r in range(R)
            for row in nodes[r, : counts[r]].tolist()
        )
        for round_i in (0, 1, 3):
            nd2, c2, m_out = (
                np.asarray(x)
                for x in _run_action(
                    action, make_rank_mesh(R), jnp.asarray(nodes),
                    jnp.asarray(counts),
                    jnp.asarray(round_i, jnp.int32),
                    t_slots=t_slots, capacity=capacity, phys_rows=phys_rows,
                )
            )
            assert (c2 >= 0).all() and (c2 <= capacity).all()
            assert c2.sum() == counts.sum()  # count conservation
            after = sorted(
                tuple(row)
                for r in range(R)
                for row in nd2[r, : c2[r]].tolist()
            )
            assert after == before, (
                f"{action} round={round_i} counts={counts.tolist()} "
                "changed the live-row multiset"
            )
            assert (m_out >= 0).all() and (m_out <= t_slots).all()
            if action == "skip":
                assert (m_out == 0).all() and (c2 == counts).all()


# -- the controller's policy, host-side ----------------------------------------


def test_controller_forced_skip_one_rank_and_drained():
    """The satellite's two zero-dispatch regimes at the decision layer:
    a 1-rank mesh and a fully drained frontier skip unconditionally, in
    EVERY mode (adaptive and all three static policies)."""
    for base, adaptive in (
        ("ring", False), ("pair", False), ("steal", False), ("pair", True),
    ):
        one = bal.BalanceController(
            num_ranks=1, k=8, t_slots=16, base=base, adaptive=adaptive
        )
        for _ in range(3):
            assert one.decide(np.array([100])) == "skip"
        multi = bal.BalanceController(
            num_ranks=4, k=8, t_slots=16, base=base, adaptive=adaptive
        )
        for _ in range(3):
            assert multi.decide(np.zeros(4)) == "skip"  # drained


def test_controller_dead_band_and_worth_floor():
    c = bal.BalanceController(num_ranks=4, k=8, t_slots=16, base="pair")
    # balanced occupancy: CV under the dead-band
    assert c.decide(np.array([100, 101, 99, 100])) == "skip"
    # skewed but nothing worth moving: every rank below k, zero pool
    assert c.decide(np.array([3, 0, 0, 0])) == "skip"
    # mild skew above the dead-band with a worthwhile transfer: base action
    assert c.decide(np.array([100, 100, 30, 2])) in ("pair", "steal")
    # static mode ignores the dead-band entirely
    s = bal.BalanceController(
        num_ranks=4, k=8, t_slots=16, base="ring", adaptive=False
    )
    assert s.decide(np.array([100, 101, 99, 100])) == "ring"


def test_controller_escalates_on_starvation_and_probe_demotes():
    starved = np.array([300, 200, 100, 0])
    # no probe: starvation escalates straight to steal
    c = bal.BalanceController(num_ranks=4, k=8, t_slots=16, base="pair")
    assert c.decide(starved) == "steal"
    # entering steal consults the probe; all-dead donors demote to base
    c = bal.BalanceController(num_ranks=4, k=8, t_slots=16, base="pair")
    assert c.decide(starved, alive_probe=lambda: np.zeros(4)) == "pair"
    # live surplus confirmed: steal stands
    c = bal.BalanceController(num_ranks=4, k=8, t_slots=16, base="pair")
    assert c.decide(starved, alive_probe=lambda: starved.copy()) == "steal"
    assert c.summary()["alive_probes"] == 1


def test_controller_probe_throttled_while_steal_stands():
    """The probe is a collective readback: a STANDING escalation must not
    re-pay it every round — entry plus every probe_every-th steal round."""
    starved = np.array([300, 200, 100, 0])
    c = bal.BalanceController(
        num_ranks=4, k=8, t_slots=16, base="pair", probe_every=16
    )
    calls = []

    def probe():
        calls.append(1)
        return starved.copy()

    for _ in range(16):
        assert c.decide(starved, alive_probe=probe) == "steal"
    assert len(calls) == 1  # entry only
    assert c.decide(starved, alive_probe=probe) == "steal"
    assert len(calls) == 2  # the 16th standing round re-checks
    assert c.summary()["alive_probes"] == 2


def test_controller_settle_hysteresis_and_accounting():
    c = bal.BalanceController(
        num_ranks=4, k=8, t_slots=16, base="pair", settle=2
    )
    skewed = np.array([300, 200, 100, 0])
    calm = np.array([100, 100, 100, 100])
    assert c.decide(skewed) == "steal"
    # first calm decision after an active action: held at base, not skip
    assert c.decide(calm) == "pair"
    # second consecutive calm decision: the collective stands down
    assert c.decide(calm) == "skip"
    assert c.last_action == "skip"
    # leaving skip is immediate
    assert c.decide(skewed) == "steal"
    c.record(0, "steal", np.array([4, 0, 0, 0]))
    c.record(1, "skip", np.zeros(4))
    s = c.summary()
    assert s["moved_rows_total"] == 4
    assert s["collective_dispatches"] == 1
    assert s["actions"] == {"steal": 1, "skip": 1}
    assert s["switches"] >= 3
    d = bal.BalanceController(num_ranks=4, k=8, t_slots=16, base="pair")
    assert d.decide(skewed) == "steal"
    assert d.degrade() == "pair"  # injected balance.steal fault absorbed
    assert d.summary()["steal_degraded"] == 1


# -- the closed loop, end to end -----------------------------------------------

_SOLVE_KW = dict(
    capacity_per_rank=256, k=8, inner_steps=1, bound="min-out",
    mst_prune=False, node_ascent=0, device_loop=False,
    max_iters=2_000_000,
)


def test_sharded_one_rank_mesh_zero_balance_dispatches():
    """Regression for the satellite's first zero-dispatch regime: on a
    1-rank mesh NO balance collective is ever dispatched, in adaptive and
    static modes alike, and the solve still proves the exact optimum."""
    d = random_d(11, 3)
    hk, _ = solve_blocks_from_dists(d[None])
    mesh = make_rank_mesh(1)
    for mode in ("adaptive", "ring"):
        res = bb.solve_sharded(d, mesh, balance=mode, **_SOLVE_KW)
        assert res.proven_optimal and res.cost == float(hk[0])
        assert res.balance["collective_dispatches"] == 0
        assert set(res.balance["actions"]) <= {"skip"}
        assert res.balance["moved_rows_total"] == 0


def test_sharded_balanced_mesh_zero_balance_dispatches():
    """The acceptance criterion's balanced control: on a rank-symmetric
    instance the adaptive controller must keep its hands off — zero
    collectives, with the skip dead-band actually exercised — while the
    solve still proves."""
    d = symmetric_d()
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve_sharded(
        d, make_rank_mesh(4), balance="adaptive", seed_mode="round-robin",
        capacity_per_rank=160, k=4, inner_steps=2, bound="min-out",
        mst_prune=False, node_ascent=0, device_loop=False, transfer=4,
        max_iters=2_000_000,
    )
    assert res.proven_optimal and res.cost == float(hk[0])
    assert res.balance["collective_dispatches"] == 0
    assert res.balance["actions"].get("skip", 0) > 0
    assert res.balance["moved_rows_total"] == 0


@pytest.mark.slow
def test_sharded_adaptive_rebalances_and_stays_exact():
    """Adversarial single-rank seeding: the adaptive controller must
    actually escalate (steal dispatched, rows moved) and the result must
    be bit-identical to the static ring's proven optimum — balance moves
    rows, never correctness."""
    d = random_d(12, 33)
    hk, _ = solve_blocks_from_dists(d[None])
    mesh = make_rank_mesh(4)
    kw = dict(_SOLVE_KW, seed_mode="single-rank")
    ring = bb.solve_sharded(d, mesh, balance="ring", **kw)
    ada = bb.solve_sharded(d, mesh, balance="adaptive", **kw)
    assert ring.proven_optimal and ada.proven_optimal
    assert ada.cost == ring.cost == float(hk[0])
    assert ada.lower_bound == ring.lower_bound
    b = ada.balance
    assert b["mode"] == "adaptive"
    assert b["collective_dispatches"] > 0
    assert b["actions"].get("steal", 0) > 0  # starvation escalated
    assert b["moved_rows_total"] > 0
    # bytes accounting is rows x the packed row width (layout-owned)
    assert b["moved_bytes_total"] % b["moved_rows_total"] == 0
    assert b["moved_bytes_total"] > b["moved_rows_total"]
    assert len(b["rows"]) > 0 and b["cv_max"] > 0
    # static mode shares the accounting path: the ring reports too
    assert ring.balance["mode"] == "ring"
    assert ring.balance["collective_dispatches"] > 0


@pytest.mark.slow
def test_sharded_repeat_solve_no_retrace_on_mode_switches():
    """The acceptance criterion's RecompilationGuard gate: a second
    same-config adaptive solve — with the controller switching actions
    mid-run — must reuse the per-action executables from the first solve
    with ZERO new jit cache entries and the SAME precompiled objects."""
    d = random_d(12, 33)
    mesh = make_rank_mesh(4)
    kw = dict(_SOLVE_KW, seed_mode="single-rank")
    res1 = bb.solve_sharded(d, mesh, balance="adaptive", **kw)
    key, entries = next(reversed(bb._SHARD_ENTRIES.items()))
    aot_before = dict(entries["aot"])
    jits = dict(entries["jit"])
    assert set(jits) >= {"skip", "pair", "steal"}  # per-action entries
    with RecompilationGuard(jits, limit=0):
        res2 = bb.solve_sharded(d, mesh, balance="adaptive", **kw)
    assert res2.proven_optimal and res2.cost == res1.cost
    assert res2.balance["switches"] >= 1  # modes DID switch mid-solve
    # the entry set is the same object, with the same compiled actions
    assert next(reversed(bb._SHARD_ENTRIES.items()))[0] == key
    after = bb._SHARD_ENTRIES[key]["aot"]
    assert set(after) == set(aot_before)
    for a, compiled in aot_before.items():
        assert after[a] is compiled, f"action {a!r} recompiled"

"""Unit tests for the packed-frontier layout (round 4).

The engine stores the B&B frontier as ONE [F, n + W + 4] int32 buffer
(branch_bound.Frontier); these tests pin the layout invariants the rest
of the code relies on: the width inversion, the host pack/unpack
round-trip, the property views, and bitcast exactness for every f32
value class (the bound comparisons must see the EXACT stored floats).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb


def test_layout_inverts_width_for_all_supported_n():
    for n in range(3, bb.MAX_BNB_CITIES + 1):
        w = (n + 31) // 32
        assert bb._layout(n + w + 4) == (n, w)


def test_layout_rejects_impossible_width():
    # n + ceil(n/32) + 4 skips some integers (e.g. the step at n=32->33
    # adds 2); such widths have no valid layout
    valid = {n + (n + 31) // 32 + 4 for n in range(1, 400)}
    for cols in range(8, 120):
        if cols not in valid:
            with pytest.raises(ValueError):
                bb._layout(cols)
            return
    pytest.skip("no invalid width in range (unexpected)")


def _random_fields(rng, m, n):
    w = (n + 31) // 32
    return {
        "path": rng.integers(0, n, size=(m, n)).astype(np.int32),
        "mask": rng.integers(0, 2**32, size=(m, w), dtype=np.uint64).astype(
            np.uint32
        ),
        "depth": rng.integers(1, n + 1, size=m).astype(np.int32),
        "cost": rng.normal(size=m).astype(np.float32) * 1e3,
        "bound": rng.normal(size=m).astype(np.float32) * 1e3,
        "sum_min": rng.normal(size=m).astype(np.float32) * 1e3,
    }


def test_pack_unpack_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    for n in (3, 31, 32, 33, 51, 100, 200):
        f = _random_fields(rng, 17, n)
        # exercise every f32 value class, incl. the sign of zero and inf
        f["bound"][0] = np.float32(np.inf)
        f["bound"][1] = np.float32(-0.0)
        f["cost"][2] = np.float32(np.nan)
        rows = bb._pack_rows_np(
            f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
        )
        assert rows.dtype == np.int32
        back = bb._unpack_rows_np(rows)
        for k in f:
            # bit-level equality (NaN-safe): compare the raw words
            a = np.asarray(f[k])
            b = np.asarray(back[k])
            assert a.dtype == b.dtype, k
            assert np.array_equal(
                a.view(np.int32) if a.dtype != np.int32 else a,
                b.view(np.int32) if b.dtype != np.int32 else b,
            ), k


def test_property_views_match_unpack():
    rng = np.random.default_rng(1)
    n = 51
    f = _random_fields(rng, 9, n)
    rows = bb._pack_rows_np(
        f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
    )
    fr = bb.Frontier(
        jnp.asarray(rows), jnp.asarray(9, jnp.int32), jnp.asarray(False)
    )
    assert np.array_equal(np.asarray(fr.path), f["path"])
    assert np.array_equal(np.asarray(fr.mask), f["mask"])
    assert np.array_equal(np.asarray(fr.depth), f["depth"])
    for k in ("cost", "bound", "sum_min"):
        assert np.array_equal(
            np.asarray(getattr(fr, k)).view(np.int32),
            f[k].view(np.int32),
        ), k


def test_property_views_on_stacked_rank_dim():
    # the sharded path stacks [R, F, cols]; the ellipsis-based views must
    # keep leading dims
    rng = np.random.default_rng(2)
    n = 14
    f = _random_fields(rng, 6, n)
    rows = bb._pack_rows_np(
        f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
    )
    stacked = np.stack([rows, rows + 0])
    fr = bb.Frontier(
        jnp.asarray(stacked),
        jnp.asarray([6, 6], jnp.int32),
        jnp.asarray([False, False]),
    )
    assert fr.path.shape == (2, 6, n)
    assert fr.bound.shape == (2, 6)
    assert np.array_equal(np.asarray(fr.path)[1], f["path"])


def test_make_root_frontier_views():
    min_out = np.asarray([0.0, 1.5, 2.5, 3.0], np.float64)
    fr = bb.make_root_frontier(4, 32, min_out)
    assert int(fr.count) == 1
    assert not bool(fr.overflow)
    assert int(fr.depth[0]) == 1
    assert int(fr.mask[0, 0]) == 1  # city 0 visited
    assert float(fr.cost[0]) == 0.0
    assert float(fr.bound[0]) == 0.0
    assert float(fr.sum_min[0]) == np.float32(min_out[1:].sum())
    # dead rows are all-zero == float 0.0 fields
    assert float(fr.bound[5]) == 0.0

"""Unit tests for the packed-frontier layout (round 4; v2 in ISSUE 8).

The engine stores the B&B frontier as ONE [F, P + W + 4] int32 buffer
(branch_bound.Frontier) with the tour prefix int8-packed 4 city ids per
word; these tests pin the layout invariants the rest of the code relies
on: the width inversion (unique (P, W) cell; exact n threaded where it
matters), the host pack/unpack round-trip, the path byte-packing, the
property views, and bitcast exactness for every f32 value class (the
bound comparisons must see the EXACT stored floats).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb


def _width(n: int) -> int:
    return bb._path_words(n) + (n + 31) // 32 + 4


def test_layout_inverts_width_cell_for_all_supported_n():
    for n in range(3, bb.MAX_BNB_CITIES + 1):
        w = (n + 31) // 32
        n_hi, w_got = bb._layout(_width(n))
        # the exact n is ambiguous within a path-word cell, but the
        # OFFSETS (P, W) — everything the views need — are unique
        assert w_got == w
        assert bb._path_words(n_hi) == bb._path_words(n)
        lo, hi = bb._layout_n_range(_width(n))
        assert lo <= n <= hi
        assert hi == n_hi


def test_layout_rejects_impossible_width():
    valid = {_width(n) for n in range(1, 400)}
    checked = 0
    for cols in range(6, 80):
        if cols not in valid:
            with pytest.raises(ValueError):
                bb._layout(cols)
            checked += 1
    assert checked, "no invalid width in range (unexpected)"


def test_path_pack_roundtrip_and_pad_lanes():
    rng = np.random.default_rng(3)
    for n in (3, 4, 5, 51, 100, 199, 200):
        path = rng.integers(0, n, size=(11, n)).astype(np.int32)
        words = bb._pack_path_np(path, n)
        assert words.dtype == np.int32
        assert words.shape == (11, bb._path_words(n))
        assert np.array_equal(bb._unpack_path_np(words, n), path)
        # pad lanes past n must be zero (the byte-set kernels rely on it)
        full = bb._unpack_path_np(words, bb._path_words(n) * bb.PATH_PACK)
        assert not full[:, n:].any()


def test_path_byte_get_matches_unpack():
    rng = np.random.default_rng(4)
    n = 51
    path = rng.integers(0, n, size=(9, n)).astype(np.int32)
    words = jnp.asarray(bb._pack_path_np(path, n))
    pos = jnp.asarray(rng.integers(0, n, size=9).astype(np.int32))
    got = np.asarray(bb._path_byte_get(words, pos))
    assert np.array_equal(got, path[np.arange(9), np.asarray(pos)])


def _random_fields(rng, m, n):
    w = (n + 31) // 32
    return {
        "path": rng.integers(0, n, size=(m, n)).astype(np.int32),
        "mask": rng.integers(0, 2**32, size=(m, w), dtype=np.uint64).astype(
            np.uint32
        ),
        "depth": rng.integers(1, n + 1, size=m).astype(np.int32),
        "cost": rng.normal(size=m).astype(np.float32) * 1e3,
        "bound": rng.normal(size=m).astype(np.float32) * 1e3,
        "sum_min": rng.normal(size=m).astype(np.float32) * 1e3,
    }


def test_pack_unpack_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    for n in (3, 31, 32, 33, 51, 100, 200):
        f = _random_fields(rng, 17, n)
        # exercise every f32 value class, incl. the sign of zero and inf
        f["bound"][0] = np.float32(np.inf)
        f["bound"][1] = np.float32(-0.0)
        f["cost"][2] = np.float32(np.nan)
        rows = bb._pack_rows_np(
            f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
        )
        assert rows.dtype == np.int32
        assert rows.shape[-1] == _width(n)
        back = bb._unpack_rows_np(rows, n=n)
        for k in f:
            # bit-level equality (NaN-safe): compare the raw words
            a = np.asarray(f[k])
            b = np.asarray(back[k])
            assert a.dtype == b.dtype, k
            assert np.array_equal(
                a.view(np.int32) if a.dtype != np.int32 else a,
                b.view(np.int32) if b.dtype != np.int32 else b,
            ), k


def test_property_views_match_unpack():
    rng = np.random.default_rng(1)
    n = 51
    f = _random_fields(rng, 9, n)
    rows = bb._pack_rows_np(
        f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
    )
    fr = bb.Frontier(
        jnp.asarray(rows), jnp.asarray(9, jnp.int32), jnp.asarray(False)
    )
    # .path unpacks to the layout-max n (pad lanes zero); slice to n
    assert np.array_equal(np.asarray(fr.path)[:, :n], f["path"])
    assert not np.asarray(fr.path)[:, n:].any()
    assert np.array_equal(np.asarray(fr.path_view(n)), f["path"])
    assert np.array_equal(np.asarray(fr.mask), f["mask"])
    assert np.array_equal(np.asarray(fr.depth), f["depth"])
    for k in ("cost", "bound", "sum_min"):
        assert np.array_equal(
            np.asarray(getattr(fr, k)).view(np.int32),
            f[k].view(np.int32),
        ), k


def test_property_views_on_stacked_rank_dim():
    # the sharded path stacks [R, F, cols]; the ellipsis-based views must
    # keep leading dims
    rng = np.random.default_rng(2)
    n = 14
    f = _random_fields(rng, 6, n)
    rows = bb._pack_rows_np(
        f["path"], f["mask"], f["depth"], f["cost"], f["bound"], f["sum_min"]
    )
    stacked = np.stack([rows, rows + 0])
    fr = bb.Frontier(
        jnp.asarray(stacked),
        jnp.asarray([6, 6], jnp.int32),
        jnp.asarray([False, False]),
    )
    assert fr.path_view(n).shape == (2, 6, n)
    assert fr.bound.shape == (2, 6)
    assert np.array_equal(np.asarray(fr.path_view(n))[1], f["path"])


def test_make_root_frontier_views():
    min_out = np.asarray([0.0, 1.5, 2.5, 3.0], np.float64)
    fr = bb.make_root_frontier(4, 32, min_out)
    assert int(fr.count) == 1
    assert not bool(fr.overflow)
    assert int(fr.depth[0]) == 1
    assert int(fr.mask[0, 0]) == 1  # city 0 visited
    assert float(fr.cost[0]) == 0.0
    assert float(fr.bound[0]) == 0.0
    assert float(fr.sum_min[0]) == np.float32(min_out[1:].sum())
    # dead rows are all-zero == float 0.0 fields
    assert float(fr.bound[5]) == 0.0


def test_row_bytes_shrink_vs_v1_layout():
    # the point of v2: node-row bytes shrink >= 1.5x at every TSPLIB
    # size we run (3.27x at kroA100) — the same ratio SpillStats
    # bytes/event and checkpoint payloads shrink by
    for n, floor in ((51, 1.5), (100, 3.0), (200, 3.0)):
        v1 = n + (n + 31) // 32 + 4
        v2 = _width(n)
        assert v1 / v2 >= floor, (n, v1, v2)


def test_layout_version_exported():
    from tsp_mpi_reduction_tpu.perf import compile_cache

    assert bb.FRONTIER_LAYOUT_VERSION == compile_cache.FRONTIER_LAYOUT_VERSION
    assert bb.FRONTIER_LAYOUT_VERSION >= 2

"""Branch-and-bound engine: optimality, invariants, checkpointing, sharding."""

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
from tsp_mpi_reduction_tpu.utils.tsplib import burma14


def random_d(n, seed):
    xy = np.random.default_rng(seed).uniform(0, 100, (n, 2))
    return np.sqrt(((xy[:, None] - xy[None]) ** 2).sum(-1))


def test_matches_held_karp_random():
    for seed in (0, 1):
        d = random_d(12, seed)
        hk, _ = solve_blocks_from_dists(d[None])
        res = bb.solve(d, capacity=1 << 14, k=64)
        assert res.proven_optimal
        assert abs(res.cost - float(hk[0])) < 1e-3
        # reported tour measures to the reported cost
        assert abs(bb.tour_cost(d, res.tour) - res.cost) < 1e-3
        assert sorted(res.tour[:-1].tolist()) == list(range(12))


def test_matches_held_karp_integer_metric():
    """Integral metrics take the fixed-point-exact path with ceil-aware
    pruning (prune at bound > inc - 1); optimality must be preserved."""
    for seed in (0, 1, 2):
        d = np.rint(random_d(12, seed) * 10)
        hk, _ = solve_blocks_from_dists(d[None])
        for mst in (True, False):
            res = bb.solve(d, capacity=1 << 14, k=64, mst_prune=mst)
            assert res.proven_optimal
            assert res.cost == float(np.rint(hk[0])) == float(hk[0])
            assert res.root_lower_bound <= res.cost
            assert res.root_lower_bound == int(res.root_lower_bound)


def test_integer_metric_min_out_matches():
    """Weak-bound (min-out) mode on an integer metric: the search — not the
    incumbent heuristic — must prove the optimum (fixed-point ceil pruning
    with pi = 0)."""
    d = np.rint(random_d(11, 7) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve(d, capacity=1 << 14, k=64, bound="min-out")
    assert res.proven_optimal and res.cost == float(hk[0])


def test_float_slack_large_scale():
    """Float metrics get a worst-case f32 rounding slack (ADVICE r1 medium):
    with distances at scale ~1e6, where naive f32 bounds would overshoot,
    optimality vs the f64 Held-Karp oracle must still hold."""
    d = random_d(12, 9) * 1e4  # coords ~1e6-scale distances after *1e4
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve(d, capacity=1 << 14, k=64)
    assert res.proven_optimal
    assert abs(res.cost - float(hk[0])) < 1e-2 * 1e4


def test_mst_bound_node_efficiency():
    """The per-node MST re-bound must expand far fewer nodes than the
    incremental bound alone on the same instance; the per-node mini-ascent
    (extra subgradient steps) must preserve exactness and not expand more."""
    d = np.rint(random_d(13, 11) * 10)
    weak = bb.solve(d, capacity=1 << 15, k=64, mst_prune=False)
    strong = bb.solve(d, capacity=1 << 15, k=64, mst_prune=True, node_ascent=0)
    ascent = bb.solve(d, capacity=1 << 15, k=64, mst_prune=True, node_ascent=3)
    assert weak.proven_optimal and strong.proven_optimal and ascent.proven_optimal
    assert weak.cost == strong.cost == ascent.cost
    assert strong.nodes_expanded <= weak.nodes_expanded
    assert ascent.nodes_expanded <= strong.nodes_expanded


@pytest.mark.slow
def test_burma14_proven_optimal():
    d = burma14().distance_matrix()
    res = bb.solve(d, capacity=1 << 15, k=128)
    assert res.cost == 3323.0 and res.proven_optimal
    assert res.nodes_expanded > 0 and res.nodes_per_sec > 0


@pytest.mark.slow
def test_sharded_burma14(goldens_dir):
    d = burma14().distance_matrix()
    res = bb.solve_sharded(d, make_rank_mesh(8), capacity_per_rank=1 << 14, k=64)
    assert res.cost == 3323.0 and res.proven_optimal


def test_tiny_capacity_spills_and_still_proves():
    """Frontier overflow recovery (VERDICT r2 item 4): a capacity far below
    the search's natural frontier must spill to the host reservoir and
    STILL end proven_optimal — never the old permanent exactness-lost flag.
    min-out + no MST pruning maximizes frontier pressure."""
    d = np.rint(random_d(12, 21) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    # inner_steps*k*(n-1) = 1*8*11 = 88 <= capacity/2 = 128: kernel overflow
    # is unreachable, so every node flows through the reservoir instead
    res = bb.solve(d, capacity=256, k=8, inner_steps=1, bound="min-out",
                   mst_prune=False, max_iters=2_000_000)
    assert res.proven_optimal
    assert res.cost == float(hk[0])


def test_spill_checkpoint_roundtrip(tmp_path):
    """A checkpoint taken while nodes sit in the host reservoir must carry
    them; resuming must still prove the exact optimum."""
    d = np.rint(random_d(12, 22) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    ck = str(tmp_path / "spill.npz")
    partial = bb.solve(d, capacity=256, k=8, inner_steps=1, bound="min-out",
                       mst_prune=False, max_iters=40, checkpoint_path=ck)
    assert not partial.proven_optimal
    resumed = bb.solve(d, capacity=256, k=8, inner_steps=1, bound="min-out",
                       mst_prune=False, max_iters=2_000_000, resume_from=ck)
    assert resumed.proven_optimal and resumed.cost == float(hk[0])


def test_resume_with_larger_k_sheds_overhang(tmp_path):
    """A checkpoint written at small k resumed with a LARGER k shrinks the
    logical capacity (the buffer's trailing k*n rows are the push block's
    write padding) — the pre-dispatch shed must spill the overhang to the
    reservoir so the unguarded first batch can never clamp its block
    write, and the resumed search must still prove the exact optimum."""
    d = np.rint(random_d(12, 23) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    ck = str(tmp_path / "k_mismatch.npz")
    partial = bb.solve(d, capacity=1024, k=8, inner_steps=1,
                       bound="min-out", mst_prune=False, max_iters=60,
                       checkpoint_path=ck)
    assert not partial.proven_optimal
    # k=32 -> k*n = 384 padding rows claimed out of the restored buffer
    resumed = bb.solve(d, capacity=1024, k=32, inner_steps=1,
                       bound="min-out", mst_prune=False,
                       max_iters=2_000_000, resume_from=ck)
    assert resumed.proven_optimal and resumed.cost == float(hk[0])


def test_device_loop_checkpoint_cadence(tmp_path, monkeypatch):
    """ADVICE r3 (medium): periodic device_loop checkpointing must track
    steps-since-last-save, not a modulo of ``it`` — dispatches that stop
    early (drained/full) drift ``it`` off any modulo grid, which silently
    disabled later periodic saves. Count actual save calls."""
    d = np.rint(random_d(12, 5) * 10)
    calls = []
    real_save = bb.save
    monkeypatch.setattr(
        bb, "save", lambda *a, **kw: (calls.append(1), real_save(*a, **kw))
    )
    ck = str(tmp_path / "cadence.npz")
    # min-out + small capacity: many small dispatches
    res = bb.solve(d, capacity=512, k=8, bound="min-out", mst_prune=False,
                   device_loop=True, max_iters=400, checkpoint_path=ck,
                   checkpoint_every=16)
    periodic = len(calls) - (0 if res.proven_optimal else 1)  # final save
    assert res.iterations > 64  # enough steps to cross several periods
    assert periodic >= 2, (
        f"{len(calls)} saves over {res.iterations} steps with period 16"
    )


def test_device_loop_time_to_best_in_dispatch(monkeypatch):
    """VERDICT r3 item 5: device_loop ``time_to_best`` must come from the
    kernel's improvement-step index, not the dispatch readback time — on a
    one-dispatch search the readback time equals the whole wall."""
    d = np.rint(random_d(13, 11) * 10)
    # deterministic suboptimal incumbent (identity tour): the search
    # itself must improve it at least once, inside the single dispatch
    monkeypatch.setattr(
        bb, "_initial_incumbent",
        lambda d, *a, **kw: np.concatenate(
            [np.arange(len(d)), [0]]
        ).astype(np.int32),
    )
    res = bb.solve(d, capacity=1 << 14, k=16, bound="min-out",
                   mst_prune=False, device_loop=True, max_iters=500_000)
    assert res.proven_optimal
    assert 0.0 < res.time_to_best < res.wall_seconds


@pytest.mark.slow
def test_reorder_every_exact_and_raises_interrupted_lb(tmp_path):
    """VERDICT r3 item 7: periodic best-bound-first re-sort. Must not
    change the proven optimum, and an interrupted run must leave a
    certified LB at least as high as plain DFS (it expands the
    bound-critical nodes first)."""
    d = np.rint(random_d(16, 3) * 1)  # integral metric
    kw = dict(capacity=1 << 14, k=32, bound="min-out", mst_prune=False)
    full_plain = bb.solve(d, device_loop=True, **kw)
    for mode in (True, False):
        full = bb.solve(d, device_loop=mode, reorder_every=8, **kw)
        assert full.proven_optimal and full.cost == full_plain.cost
    pa = bb.solve(d, device_loop=True, max_iters=40, **kw)
    pb = bb.solve(d, device_loop=True, max_iters=40, reorder_every=4, **kw)
    assert pb.lower_bound > pa.lower_bound  # strict on this fixture
    # cadence must survive dispatch splitting: with checkpoint-capped
    # dispatches (6 steps) smaller than would ever reach a per-dispatch
    # counter's period, the run-global step0 still fires the re-sort
    pc = bb.solve(d, device_loop=True, max_iters=40, reorder_every=4,
                  checkpoint_path=str(tmp_path / "reorder_ck.npz"),
                  checkpoint_every=6, **kw)
    assert pc.lower_bound > pa.lower_bound


def test_checkpoint_resume(tmp_path):
    d = random_d(11, 3)
    ckpt = str(tmp_path / "bnb.npz")
    partial = bb.solve(d, capacity=1 << 13, k=32, inner_steps=4, max_iters=8,
                       checkpoint_path=ckpt, checkpoint_every=4)
    assert not partial.proven_optimal  # stopped early
    resumed = bb.solve(d, capacity=1 << 13, k=32, resume_from=ckpt)
    hk, _ = solve_blocks_from_dists(d[None])
    assert resumed.proven_optimal
    assert abs(resumed.cost - float(hk[0])) < 1e-3


def test_natural_push_order_same_proof():
    """push_order="natural" (no per-step sort) must prove the same optimum
    as best-first on both the host loop and the device loop (node counts
    may differ — pop order shapes the tree while the incumbent is still
    improving)."""
    for seed in (0, 3):
        d = np.rint(random_d(13, seed) * 10)
        base = bb.solve(d, capacity=1 << 14, k=64, push_order="best-first")
        nat = bb.solve(d, capacity=1 << 14, k=64, push_order="natural")
        assert base.proven_optimal and nat.proven_optimal
        assert nat.cost == base.cost
        nat_dev = bb.solve(d, capacity=1 << 14, k=64, push_order="natural",
                           device_loop=True)
        assert nat_dev.proven_optimal and nat_dev.cost == base.cost


def test_reservoir_exchange_repartitions_globally():
    """The r5 kroA100 campaign measured a DFS-with-spill inversion: the
    reservoir held 2.65M nodes BETTER than the frontier's best, pinning
    the certified LB while the device expanded worse subtrees. exchange()
    must re-partition globally: best bounds on-device (best on top),
    worst spilled, incumbent-closed nodes dropped."""
    import jax.numpy as jnp

    n = 6
    def rows(bounds):
        m = len(bounds)
        return bb._pack_rows_np(
            np.zeros((m, n), np.int32), np.zeros((m, 1), np.uint32),
            np.full(m, 2, np.int32), np.zeros(m, np.float32),
            np.asarray(bounds, np.float32), np.zeros(m, np.float32),
        )

    fr_rows = np.zeros((10, bb._path_words(n) + 1 + 4), np.int32)
    fr_rows[:4] = rows([50.0, 40.0, 30.0, 99.0])  # 99: incumbent-closed
    fr = bb.Frontier(jnp.asarray(fr_rows), jnp.asarray(4, jnp.int32),
                     jnp.asarray(False))
    rv = bb._Reservoir()
    rv.chunks.append(rows([5.0, 7.0, 6.0]))
    out = rv.exchange(fr, inc_cost=90.0, integral=False, capacity=8)
    assert int(out.count) == 4  # min(6 alive, capacity//2=4)
    got = bb._np_bound_col(np.asarray(out.nodes[:4]))
    # stack order: worst at bottom, best on top (popped first)
    assert got.tolist() == [30.0, 7.0, 6.0, 5.0]
    assert len(rv) == 2 and rv.min_bound() == 40.0  # spilled remainder
    # nothing lost: 4 on device + 2 spilled = 6 alive (99 dropped by inc)

    # PARTIAL inversion (reservoir min between live min and live max):
    # the device already holds the global alive minimum, so the fast path
    # must fire — reservoir untouched, live rows best-half selected
    fr_rows2 = np.zeros((10, bb._path_words(n) + 1 + 4), np.int32)
    fr_rows2[:3] = rows([30.0, 50.0, 60.0])
    fr2 = bb.Frontier(jnp.asarray(fr_rows2), jnp.asarray(3, jnp.int32),
                      jnp.asarray(False))
    rv2 = bb._Reservoir()
    rv2.chunks.append(rows([35.0, 45.0]))
    out2 = rv2.exchange(fr2, inc_cost=90.0, integral=False, capacity=4)
    assert int(out2.count) == 2  # capacity//2 of the live rows only
    got2 = bb._np_bound_col(np.asarray(out2.nodes[:2]))
    assert got2.tolist() == [50.0, 30.0]  # best live on top
    # reservoir untouched by the fast path except the live cut joining it
    assert len(rv2) == 3 and rv2.min_bound() == 35.0

    # every live row dead (incumbent improved past them): the alive-
    # filtered guard sees an empty live minimum and must still run the
    # full merge so the reservoir's alive nodes come back on-device
    fr_rows3 = np.zeros((10, bb._path_words(n) + 1 + 4), np.int32)
    fr_rows3[:2] = rows([92.0, 95.0])  # both dead at inc=90
    fr3 = bb.Frontier(jnp.asarray(fr_rows3), jnp.asarray(2, jnp.int32),
                      jnp.asarray(False))
    rv3 = bb._Reservoir()
    rv3.chunks.append(rows([60.0]))
    out3 = rv3.exchange(fr3, inc_cost=90.0, integral=False, capacity=4)
    assert int(out3.count) == 1
    assert bb._np_bound_col(np.asarray(out3.nodes[:1])).tolist() == [60.0]
    assert len(rv3) == 0

    # prune GC: dead rows leave the reservoir on incumbent improvement
    rv4 = bb._Reservoir()
    rv4.chunks.append(rows([10.0, 80.0, 85.0]))
    rv4.prune(82.0, integral=False)
    assert len(rv4) == 2 and rv4.min_bound() == 10.0


def test_capped_push_block_same_proof():
    """push_block caps the per-step block write with a lax.cond full-block
    fallback — the proof and trajectory must be IDENTICAL to the uncapped
    engine (both branches write every pushed row; the cap only trims
    garbage rows), on the host loop and the device loop, including caps
    small enough that the fallback branch actually runs."""
    d = np.rint(random_d(13, 5) * 10)
    base = bb.solve(d, capacity=1 << 14, k=64, push_order="natural")
    for pb in (64, 256):  # 64 << typical n_push: fallback branch exercised
        capped = bb.solve(d, capacity=1 << 14, k=64, push_order="natural",
                          push_block=pb)
        assert capped.proven_optimal and capped.cost == base.cost
        # identical trajectory: the cap is write-shape-only
        assert capped.nodes_expanded == base.nodes_expanded
    # device loop: trajectory identity too (a capped-write bug confined to
    # _guarded_expand_steps' consumers would slip past a cost-only check)
    dev_base = bb.solve(d, capacity=1 << 14, k=64, push_order="natural",
                        device_loop=True)
    dev = bb.solve(d, capacity=1 << 14, k=64, push_order="natural",
                   push_block=256, device_loop=True)
    assert dev.proven_optimal and dev.cost == base.cost
    assert dev.nodes_expanded == dev_base.nodes_expanded
    # sharded plumbing: the capped engine under shard_map + balance
    sh = bb.solve_sharded(d, make_rank_mesh(4), capacity_per_rank=1 << 12,
                          k=16, push_block=128)
    assert sh.proven_optimal and sh.cost == base.cost
    with pytest.raises(ValueError, match="push_block"):
        bb.solve(d, capacity=1 << 14, k=64, push_block=-100, max_iters=4)


def test_pair_assignment_rotation_starves_nobody():
    """The pair-balance matching must not deterministically starve a rank.

    Adversarial shape from the measured eil51 failure: more drained ranks
    (five zeros) than rich ones (three) — a stable tie-break parks the
    same zero rank in the donor half every round, paired with another
    zero, fed nothing forever (rank 0 expanded 7 nodes of a 238k-node
    run). With the rotating tie-break, simulating the count dynamics must
    feed EVERY rank within a few rounds, conserve nodes, and never
    overflow a receiver."""
    import jax.numpy as jnp

    R, t_slots, cap = 8, 64, 1 << 10
    counts = np.array([0, 900, 0, 0, 800, 0, 700, 0], np.int32)
    fed = counts > 0
    total = counts.sum()
    for round_i in range(6):
        m_of, partner_of = bb._pair_assignment(
            jnp.asarray(counts), jnp.asarray(round_i, jnp.int32), R, t_slots
        )
        m_of, partner_of = np.asarray(m_of), np.asarray(partner_of)
        # the matching is an involution: my partner's partner is me
        np.testing.assert_array_equal(partner_of[partner_of], np.arange(R))
        # donations route donor -> its mirror; apply them
        new = counts - m_of
        for r in range(R):
            new[partner_of[r]] += m_of[r]
        counts = new
        assert (counts >= 0).all() and (counts <= cap).all()
        assert counts.sum() == total  # conservation
        fed |= counts > 0
    assert fed.all(), f"starved ranks remain: {np.where(~fed)[0]}"
    # and the balance actually flattened the skew
    assert counts.max() <= 3 * max(counts.min(), 1)


@pytest.mark.slow
def test_sharded_ring_balance_spreads_adversarial_seed():
    """VERDICT r2 item 5: with ALL root work seeded on rank 0, ring
    diffusion must spread expansion across the mesh and finish within ~2x
    the iterations of the balanced round-robin seeding."""
    d = np.rint(random_d(16, 31) * 10)
    mesh = make_rank_mesh(8)
    kw = dict(capacity_per_rank=1 << 12, k=32, inner_steps=4,
              bound="min-out", mst_prune=False)
    balanced = bb.solve_sharded(d, mesh, seed_mode="round-robin", **kw)
    skewed = bb.solve_sharded(d, mesh, seed_mode="single-rank", **kw)
    assert balanced.proven_optimal and skewed.proven_optimal
    assert balanced.cost == skewed.cost
    # work diffused: most ranks expanded nodes despite the one-rank seed
    assert (skewed.nodes_per_rank > 0).sum() >= 6
    assert skewed.iterations <= 2 * balanced.iterations + 8 * kw["inner_steps"]


def test_sharded_tiny_capacity_spills_and_still_proves():
    """Per-rank reservoirs: a sharded run whose per-rank stacks overflow
    must spill to the host and still end proven_optimal (the sharded
    analog of the single-device reservoir test)."""
    d = np.rint(random_d(12, 51) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    mesh = make_rank_mesh(4)
    res = bb.solve_sharded(d, mesh, capacity_per_rank=128, k=4, inner_steps=1,
                           bound="min-out", mst_prune=False,
                           max_iters=2_000_000)
    assert res.proven_optimal
    assert res.cost == float(hk[0])


def test_sharded_checkpoint_roundtrip(tmp_path):
    """VERDICT r2 item 9: sharded B&B checkpoint/resume on the virtual mesh.
    Resume must carry the per-rank stacks + incumbent and prove the exact
    optimum; a mismatched rank count must be refused."""
    d = np.rint(random_d(13, 41) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    mesh = make_rank_mesh(8)
    ck = str(tmp_path / "shard.npz")
    kw = dict(capacity_per_rank=1 << 11, k=16, inner_steps=2,
              bound="min-out", mst_prune=False)
    partial = bb.solve_sharded(d, mesh, max_iters=4, checkpoint_path=ck, **kw)
    assert not partial.proven_optimal
    with pytest.raises(ValueError, match="ranks"):
        bb.solve(d, resume_from=ck)
    with pytest.raises(ValueError, match="ranks"):
        bb.solve_sharded(d, make_rank_mesh(4), resume_from=ck, **kw)
    resumed = bb.solve_sharded(d, mesh, resume_from=ck, **kw)
    assert resumed.proven_optimal
    assert resumed.cost == float(hk[0])


def test_greedy_init_tools():
    d = random_d(20, 5)
    nn = bb.nearest_neighbor_tour(d)
    assert sorted(nn[:-1].tolist()) == list(range(20))
    improved = bb.two_opt(d, nn)
    assert bb.tour_cost(d, improved) <= bb.tour_cost(d, nn) + 1e-9
    assert sorted(improved[:-1].tolist()) == list(range(20))


def test_stretch_200_city_one_tree_gap():
    """BASELINE config 5 (stretch): 200-city random Euclidean + 1-tree root
    bound. Engine runs within the raised MAX_BNB_CITIES (7 mask words),
    yields a valid tour, a certified root bound, and a reportable gap."""
    assert bb.MAX_BNB_CITIES >= 200
    rng = np.random.default_rng(200)
    xy = rng.uniform(0, 1000, (200, 2))
    d = np.rint(np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1)))
    res = bb.solve(d, capacity=1 << 13, k=64, inner_steps=4, time_limit_s=20)
    tour = res.tour
    assert tour[0] == tour[-1] == 0
    assert sorted(tour[:-1].tolist()) == list(range(200))
    assert res.cost == pytest.approx(bb.tour_cost(d, tour), rel=1e-6)
    # certified bound: gap to the incumbent is finite and sane (HK 1-tree
    # is typically within a few percent on uniform instances)
    assert 0 <= res.cost - res.root_lower_bound <= 0.2 * res.cost


def test_rejects_out_of_range_n():
    with pytest.raises(ValueError):
        bb.solve(np.ones((bb.MAX_BNB_CITIES + 1,) * 2))
    with pytest.raises(ValueError):
        bb.solve(np.ones((2, 2)))


def test_target_cost_early_stop():
    d = random_d(12, 4)
    res = bb.solve(d, capacity=1 << 14, k=64, target_cost=1e9)
    assert res.iterations <= 64  # stops on first sync at target


def test_multiword_mask_circle36_proves_analytic_optimum():
    """n=36 needs two mask words; on a circle the optimal tour is the
    perimeter (visiting in angular order), so exactness is checkable."""
    n, r = 36, 100.0
    th = 2 * np.pi * np.arange(n) / n
    xy = np.stack([r * np.cos(th), r * np.sin(th)], 1)
    d = np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1))
    opt = n * 2 * r * np.sin(np.pi / n)
    res = bb.solve(d, capacity=1 << 14, k=64, inner_steps=8, time_limit_s=60)
    assert res.cost == pytest.approx(opt, rel=1e-5)
    tour = res.tour
    assert sorted(tour[:-1].tolist()) == list(range(n))
    assert res.root_lower_bound <= res.cost


def test_multiword_mask_large_instance_smoke():
    """n=52-class instance (berlin52 size): engine runs, yields a valid
    closed tour and a consistent bound, within a short time limit."""
    rng = np.random.default_rng(52)
    xy = rng.uniform(0, 1000, (52, 2))
    d = np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1))
    res = bb.solve(d, capacity=1 << 13, k=64, inner_steps=8, time_limit_s=5)
    tour = res.tour
    assert tour[0] == tour[-1] == 0
    assert sorted(tour[:-1].tolist()) == list(range(52))
    assert res.cost == pytest.approx(bb.tour_cost(d, tour), rel=1e-5)
    assert res.root_lower_bound <= res.cost
    assert res.nodes_per_sec > 0


def test_device_loop_matches_host_loop():
    """The transfer-free single-dispatch path (_solve_device) must prove
    the same optimum as the per-batch host loop."""
    d = np.rint(random_d(12, 5) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    host = bb.solve(d, capacity=1 << 14, k=64, device_loop=False)
    dev = bb.solve(d, capacity=1 << 14, k=64, device_loop=True)
    assert host.proven_optimal and dev.proven_optimal
    assert host.cost == dev.cost == float(hk[0])


def test_device_loop_compacts_and_spills_tiny_capacity():
    """At a capacity far below the natural frontier the device loop must
    compact on-device, stop full (never the lossy overflow flag), spill to
    the host reservoir between dispatches, and still end proven."""
    d = np.rint(random_d(12, 21) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    # capacity just over the 4*k*(n-1) floor so compaction pressure is real
    res = bb.solve(d, capacity=4 * 8 * 11 + 64, k=8, bound="min-out",
                   mst_prune=False, node_ascent=0, device_loop=True,
                   max_iters=2_000_000)
    assert res.proven_optimal
    assert res.cost == float(hk[0])


def test_device_loop_capacity_guard():
    d = np.rint(random_d(12, 3) * 10)
    with pytest.raises(ValueError, match="device_loop needs capacity"):
        bb.solve(d, capacity=64, k=64, device_loop=True)


def test_warm_compile_device_solver_smoke():
    """AOT warm-compile must not execute anything (it exists so benches can
    exclude compile time without a poisoning warmup run)."""
    bb.warm_compile_device_solver(12, 1 << 12, 16, True, True, 1)


def test_host_incumbent_quality():
    """strong_incumbent_host (numpy ILS twin) must produce a valid closed
    tour whose cost matches a re-measure; on burma14 it should land the
    published optimum like the device version does."""
    d = burma14().distance_matrix()
    tour = bb.strong_incumbent_host(d, starts=16)
    assert tour[0] == tour[-1] == 0
    assert sorted(tour[:-1].tolist()) == list(range(d.shape[0]))
    assert bb.tour_cost(np.asarray(d, np.float64), tour) == 3323.0


def test_host_ascent_matches_device_root_bound():
    """The f64 host ascent's certified root bound must be at least as good
    as (and close to) the published optima for bound-tight instances."""
    from tsp_mpi_reduction_tpu.ops.one_tree import held_karp_potentials_np, one_tree_value_np
    d = burma14().distance_matrix()
    pi, w = held_karp_potentials_np(np.asarray(d, np.float64), steps=400)
    assert abs(one_tree_value_np(d, pi) - w) < 1e-9
    assert 3322.0 <= w <= 3323.0  # burma14's HK bound equals its optimum


def test_device_ascent_mode_still_proves():
    """ascent="device" (the f32 jit ascent) remains a supported bound
    source for the host-loop path."""
    d = np.rint(random_d(12, 7) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve(d, capacity=1 << 14, k=64, device_loop=False, ascent="device")
    assert res.proven_optimal and res.cost == float(hk[0])


def test_sharded_device_loop_matches_host_loop():
    """The device-resident sharded loop (expand + ring balance + incumbent
    all_gather + compaction inside one dispatch) must walk the SAME search
    as the per-batch host loop — identical totals and per-rank counts."""
    d = np.rint(random_d(12, 11) * 10)
    mesh = make_rank_mesh(8)
    kw = dict(capacity_per_rank=1 << 12, k=16, inner_steps=4,
              bound="min-out", mst_prune=False, node_ascent=0,
              max_iters=2_000_000)
    host = bb.solve_sharded(d, mesh, device_loop=False, **kw)
    dev = bb.solve_sharded(d, mesh, device_loop=True, **kw)
    assert host.proven_optimal and dev.proven_optimal
    assert host.cost == dev.cost
    assert host.nodes_expanded == dev.nodes_expanded
    np.testing.assert_array_equal(host.nodes_per_rank, dev.nodes_per_rank)


def test_sharded_reorder_every_exact():
    """--reorder-every on the sharded engine (both loop modes): per-rank
    best-bound-first re-sorts must preserve the proven optimum."""
    d = np.rint(random_d(12, 11) * 10)
    mesh = make_rank_mesh(8)
    kw = dict(capacity_per_rank=1 << 12, k=16, inner_steps=4,
              bound="min-out", mst_prune=False, node_ascent=0,
              max_iters=2_000_000, reorder_every=8)
    ref = bb.solve_sharded(d, mesh, device_loop=False,
                           **{**kw, "reorder_every": 0})
    for mode in (False, True):
        res = bb.solve_sharded(d, mesh, device_loop=mode, **kw)
        assert res.proven_optimal
        assert res.cost == ref.cost


def test_sharded_device_loop_adversarial_seed_balances():
    """Work seeded on one rank must diffuse around the ring inside the
    device-resident loop (no host round trips between rounds)."""
    d = np.rint(random_d(12, 13) * 10)
    res = bb.solve_sharded(
        d, make_rank_mesh(8), capacity_per_rank=1 << 12, k=16, inner_steps=4,
        bound="min-out", mst_prune=False, node_ascent=0,
        seed_mode="single-rank", device_loop=True, max_iters=2_000_000,
    )
    assert res.proven_optimal
    assert (res.nodes_per_rank > 0).sum() >= 4


def test_sharded_device_loop_tiny_capacity_spills():
    """An irreducibly full rank must stop the in-dispatch loop intact, be
    spilled by the host reservoir, and the search must still prove."""
    d = np.rint(random_d(12, 21) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve_sharded(
        d, make_rank_mesh(4), capacity_per_rank=4 * 8 * 11 + 32, k=8,
        inner_steps=4, bound="min-out", mst_prune=False, node_ascent=0,
        device_loop=True, max_iters=2_000_000,
    )
    assert res.proven_optimal
    assert res.cost == float(hk[0])


def test_final_lower_bound_reporting():
    """An early-stopped run must report a certified global lower bound
    (min over open nodes, >= root bound, <= cost); a proven run reports
    its cost."""
    d = np.rint(random_d(12, 9) * 10)
    full = bb.solve(d, capacity=1 << 14, k=64)
    assert full.proven_optimal and full.lower_bound == full.cost
    # min-out + 1-iteration budget: stops early with an open frontier
    part = bb.solve(d, capacity=1 << 14, k=8, inner_steps=1, max_iters=3,
                    bound="min-out", mst_prune=False, node_ascent=0)
    assert not part.proven_optimal
    assert part.root_lower_bound <= part.lower_bound <= part.cost


@pytest.mark.slow
def test_chunked_driver_resumes_across_processes(tmp_path):
    """tools/bnb_chunked.py: each chunk a fresh subprocess resuming from
    checkpoint (the relay-poison workaround for long runs) — a tiny
    per-chunk budget must still converge to a proven optimum."""
    import json
    import os
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(__file__), "..", "tools", "bnb_chunked.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, tool, "burma14", "--chunk-iters=60", "--max-chunks=10",
         f"--checkpoint={tmp_path}/c.npz", "--k=64", "--capacity=8192",
         "--bound=min-out"],
        capture_output=True, text=True, env=env, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [json.loads(x) for x in r.stdout.strip().splitlines()]
    summary = lines[-1]
    assert summary["proven_optimal"] and summary["cost"] == 3323.0
    assert summary["chunks"] >= 2  # genuinely resumed at least once


def _packed_rows(n, bounds):
    """Packed frontier rows (depth 2, zero paths) with the given bounds."""
    m = len(bounds)
    return bb._pack_rows_np(
        np.zeros((m, n), np.int32), np.zeros((m, 1), np.uint32),
        np.full(m, 2, np.int32), np.zeros(m, np.float32),
        np.asarray(bounds, np.float32), np.zeros(m, np.float32),
    )


def test_reservoir_take0_respills_instead_of_dropping():
    """ADVICE r5 item 1: with capacity <= 1, capacity//2 == 0 means the
    exchange can keep NOTHING on-device — every alive node must return to
    the reservoir. Pre-fix, _partition cleared self.chunks, computed the
    merged alive rows, then returned None on take==0, silently discarding
    open nodes (a degenerate run could then claim proven_optimal with
    subtrees unexplored)."""
    import jax.numpy as jnp

    n = 6
    fr_rows = np.zeros((8, bb._path_words(n) + 1 + 4), np.int32)
    fr_rows[:3] = _packed_rows(n, [10.0, 20.0, 30.0])
    fr = bb.Frontier(jnp.asarray(fr_rows), jnp.asarray(3, jnp.int32),
                     jnp.asarray(False))
    rv = bb._Reservoir()
    rv.chunks.append(_packed_rows(n, [15.0]))
    out = rv.exchange(fr, inc_cost=90.0, integral=False, capacity=1)
    assert int(out.count) == 0
    # all 4 alive nodes live on in the reservoir — none dropped
    assert len(rv) == 4 and rv.min_bound() == 10.0
    # refill at capacity 1 also keeps them spilled rather than dropping
    out2 = rv.refill(out, inc_cost=90.0, integral=False, capacity=1)
    assert int(out2.count) == 0 and len(rv) == 4 and rv.min_bound() == 10.0
    # dead rows (above the incumbent) may still be dropped legitimately
    rv2 = bb._Reservoir()
    rv2.chunks.append(_packed_rows(n, [95.0]))
    empty = bb.Frontier(jnp.asarray(np.zeros((8, bb._path_words(n) + 1 + 4), np.int32)),
                        jnp.asarray(0, jnp.int32), jnp.asarray(False))
    out3 = rv2.exchange(empty, inc_cost=90.0, integral=False, capacity=1)
    assert int(out3.count) == 0 and len(rv2) == 0


def test_exchange_transfers_live_prefix_only():
    """ADVICE r5 item 3: exchange must not round-trip the physical buffer.
    The kept slice is written back in place — every row past ``take``
    keeps its previous device contents bit-for-bit (the old path re-
    uploaded the whole host copy) — and a no-keep exchange returns the
    original buffer object outright (zero upload)."""
    import jax.numpy as jnp

    n = 6
    fr_rows = np.zeros((12, bb._path_words(n) + 1 + 4), np.int32)
    fr_rows[:4] = _packed_rows(n, [50.0, 40.0, 30.0, 99.0])
    fr_rows[4:] = 7  # sentinel pattern in the dead region
    fr = bb.Frontier(jnp.asarray(fr_rows), jnp.asarray(4, jnp.int32),
                     jnp.asarray(False))
    rv = bb._Reservoir()
    rv.chunks.append(_packed_rows(n, [5.0, 7.0, 6.0]))
    out = rv.exchange(fr, inc_cost=90.0, integral=False, capacity=8)
    take = int(out.count)
    assert take == 4
    after = np.asarray(out.nodes)
    # dead region bit-identical to the ORIGINAL device buffer: the
    # sentinels prove no host copy of those rows was ever re-uploaded
    assert (after[take:] == 7).all()
    # all-dead live rows + empty reservoir: nothing to keep, and the very
    # buffer object is reused (no upload at all)
    rv3 = bb._Reservoir()
    dead_rows = np.zeros((6, bb._path_words(n) + 1 + 4), np.int32)
    dead_rows[:2] = _packed_rows(n, [95.0, 97.0])
    dead = bb.Frontier(jnp.asarray(dead_rows), jnp.asarray(2, jnp.int32),
                       jnp.asarray(False))
    out3 = rv3.exchange(dead, inc_cost=90.0, integral=False, capacity=8)
    assert int(out3.count) == 0 and out3.nodes is dead.nodes


def test_exchange_rows_fast_full_equivalence():
    """PR 2 property test: the exchange fast path (live-only best-half
    select, reservoir untouched) and the full merge must be EQUIVALENT in
    what survives — the global multiset of alive bounds (device keep +
    reservoir) equals the alive input multiset, so the certified minimum
    is identical — across randomized frontiers, reservoirs, incumbents
    and capacities including the degenerate capacity<=1 / take==0 edges
    fixed in PR 1. They may split the survivors differently (that is the
    point: the fast path skips the reservoir concatenate), but neither
    may drop an open node or resurrect a closed one."""
    rng = np.random.default_rng(7)
    n = 6
    inc = 50.0
    for trial in range(60):
        capacity = int(rng.choice([1, 2, 3, 5, 8, 16, 64]))
        n_live = int(rng.integers(0, 13))
        live_b = np.round(rng.uniform(0, 100, n_live).astype(np.float32), 2)
        chunk_bounds = [
            np.round(rng.uniform(0, 100, int(rng.integers(0, 7))).astype(np.float32), 2)
            for _ in range(int(rng.integers(0, 4)))
        ]
        alive_in = sorted(
            float(b)
            for arr in [live_b] + chunk_bounds
            for b in arr
            if b < inc
        )

        outs = {}
        for merge in (False, True):
            rv = bb._Reservoir()
            for cb in chunk_bounds:
                if cb.size:
                    rv.chunks.append(_packed_rows(n, cb))
            live = _packed_rows(n, live_b) if n_live else np.zeros(
                (0, bb._path_words(n) + 1 + 4), np.int32
            )
            keep = rv.exchange_rows(live, inc, False, capacity, merge=merge)
            kept_b = (
                [] if keep is None
                else bb._np_bound_col(keep).astype(float).tolist()
            )
            res_b = [
                float(b) for c in rv.chunks for b in bb._np_bound_col(c)
            ]
            outs[merge] = (kept_b, res_b)
            # the kept slice never exceeds the best-half budget and holds
            # only alive rows
            assert len(kept_b) <= capacity // 2
            assert all(b < inc for b in kept_b)
            if merge:
                # the full merge also drops closed reservoir rows, so its
                # surviving multiset is exactly the alive inputs
                assert sorted(kept_b + res_b) == alive_in, (trial, merge)
            else:
                # fast path: alive survivors identical; dead reservoir
                # rows may additionally linger until the next prune/merge
                alive_out = sorted(b for b in kept_b + res_b if b < inc)
                assert alive_out == alive_in, (trial, merge)
        if alive_in:
            # identical certified minimum over the open set either way
            for merge, (kept_b, res_b) in outs.items():
                alive_out = [b for b in kept_b + res_b if b < inc]
                assert min(alive_out) == alive_in[0], (trial, merge)


def test_sharded_spill_counters_fast_path():
    """Acceptance: the sharded fast path transfers only live-prefix bytes.
    A spill-heavy sharded run must (a) still prove, (b) record spill
    traffic strictly below the pre-PR-2 full-buffer round trip, (c) bound
    the host-ward bytes by live-prefix size (<= capacity rows per event —
    never the physical buffer with its k*n padding rows), and (d) take
    the full reservoir merge only on a minority of events (the inversion
    case), not every spill (ADVICE r5 item 2)."""
    d = np.rint(random_d(13, 51) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    ranks, cap, k, n = 4, 128, 4, 13
    res = bb.solve_sharded(
        d, make_rank_mesh(ranks), capacity_per_rank=cap, k=k, inner_steps=1,
        bound="min-out", mst_prune=False, node_ascent=0, max_iters=2_000_000,
    )
    assert res.proven_optimal and res.cost == float(hk[0])
    assert res.spill_rounds > 0 and res.spill_events >= res.spill_rounds
    width = bb._path_words(n) + 1 + 4
    live_prefix_cap = res.spill_events * cap * width * 4
    phys_roundtrip = res.spill_rounds * 2 * ranks * (cap + k * n) * width * 4
    assert 0 < res.spill_bytes_to_host <= live_prefix_cap
    assert 0 < res.spill_bytes_to_device <= live_prefix_cap
    total = res.spill_bytes_to_host + res.spill_bytes_to_device
    assert total < phys_roundtrip  # strictly beats HEAD's full round trip
    assert res.spill_full_merges < res.spill_events  # fast path dominates


def test_lb_certified_monotone_across_resumed_chunks(tmp_path):
    """Satellite: the reported certified LB must never regress across a
    chunked (checkpoint/resume) campaign — each chunk's lower_bound is
    clamped to the running max the checkpoint carries; lb_raw stays the
    chunk's own min-over-open value (<= the certified one)."""
    d = np.rint(random_d(12, 33) * 10)
    ck = str(tmp_path / "mono.npz")
    kw = dict(capacity=1 << 13, k=8, inner_steps=1, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False)
    res = bb.solve(d, max_iters=3, checkpoint_path=ck, **kw)
    assert not res.proven_optimal
    assert res.lower_bound >= res.lower_bound_raw
    prev = res.lower_bound
    for _ in range(4):
        res = bb.solve(d, max_iters=3, resume_from=ck, checkpoint_path=ck,
                       **kw)
        assert res.lower_bound >= prev  # monotone, chunk over chunk
        assert res.lower_bound >= res.lower_bound_raw
        assert res.lower_bound <= res.cost
        prev = res.lower_bound
        if res.proven_optimal:
            break
    # the checkpoint itself carries the certified floor
    if not res.proven_optimal:
        *_, lb0 = bb.restore(ck, expect_d=d, expect_bound="min-out")
        assert lb0 == pytest.approx(res.lower_bound)


def test_sharded_lb_certified_monotone(tmp_path):
    """The sharded engine honors the same certified-LB floor contract."""
    d = np.rint(random_d(12, 34) * 10)
    mesh = make_rank_mesh(4)
    ck = str(tmp_path / "mono_shard.npz")
    kw = dict(capacity_per_rank=1 << 11, k=8, inner_steps=1,
              bound="min-out", mst_prune=False, node_ascent=0)
    res = bb.solve_sharded(d, mesh, max_iters=2, checkpoint_path=ck, **kw)
    assert not res.proven_optimal
    prev = res.lower_bound
    for _ in range(3):
        res = bb.solve_sharded(d, mesh, max_iters=2, resume_from=ck,
                               checkpoint_path=ck, **kw)
        assert res.lower_bound >= prev
        assert res.lower_bound >= res.lower_bound_raw
        prev = res.lower_bound
        if res.proven_optimal:
            break


def test_degenerate_capacity_run_stays_honest():
    """Degenerate-config regression for the take==0 fix: at capacity 1-2
    (capacity//2 <= 1) the engine crawls through the reservoir one node
    at a time — whatever it manages, a claimed proven_optimal must be the
    true optimum (pre-fix, dropped nodes could fake the proof), and runs
    that stop early must say so."""
    for seed in (0, 1):
        d = np.rint(random_d(6, seed) * 10)
        hk, _ = solve_blocks_from_dists(d[None])
        for cap in (1, 2):
            res = bb.solve(d, capacity=cap, k=1, inner_steps=1,
                           bound="min-out", mst_prune=False,
                           max_iters=50_000, device_loop=False)
            if res.proven_optimal:
                assert res.cost == float(hk[0]), (seed, cap)
            else:
                # honest non-proof: the certified bound cannot have closed
                assert res.lower_bound <= res.cost

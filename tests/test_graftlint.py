"""graftlint static analysis + runtime contract layer.

Three surfaces:
- rule fixtures: each of R1-R5 fires on its hazard snippet and stays quiet
  on the clean rewrite (the lint must earn its exit code);
- the meta-machinery: inline disables, hot markers, the line-free baseline;
- the runtime layer: Frontier/PaddedTour boundary contracts and the jit
  recompilation guard, including the guard failing a loop that re-jits a
  fixed-shape entry point every call.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.analysis import contracts, graftlint
from tsp_mpi_reduction_tpu.analysis.__main__ import main as graftlint_main
from tsp_mpi_reduction_tpu.models import branch_bound as bb

pytestmark = pytest.mark.lint  # `pytest -m lint` = fast pre-push gate


def lint(src, **kw):
    return graftlint.lint_text(textwrap.dedent(src), "fixture.py", **kw)


def rules_of(violations):
    return sorted({v.rule for v in violations})


# -- R1: device->host pull in a hot loop -------------------------------------

R1_LOOP = """
    import numpy as np

    def drain(fr, steps):
        for _ in range(steps):
            host = np.asarray(fr.nodes)
        return host
"""


def test_r1_fires_on_pull_in_loop():
    vs = lint(R1_LOOP)
    assert rules_of(vs) == ["R1"] and vs[0].scope == "drain"


def test_r1_fires_in_default_hot_path_without_lexical_loop():
    vs = lint(
        """
        import numpy as np

        def exchange(fr):
            return np.asarray(fr.nodes)
        """
    )
    assert rules_of(vs) == ["R1"]


def test_r1_hot_marker_promotes_function():
    src = """
        import numpy as np

        def fetch(fr):  # graftlint: hot
            return np.asarray(fr.nodes)
    """
    assert rules_of(lint(src)) == ["R1"]
    # same body, no marker, not a known hot path: quiet
    assert lint(src.replace("  # graftlint: hot", "")) == []


def test_r1_fires_on_device_copy_in_loop():
    vs = lint(
        """
        import jax.numpy as jnp
        import numpy as np

        def churn(steps):
            buf = jnp.zeros((4, 4))
            out = []
            while steps:
                out.append(buf.copy())
                steps -= 1
            return out
        """
    )
    assert "R1" in rules_of(vs)


def test_r1_quiet_on_host_arrays():
    assert (
        lint(
            """
            import numpy as np

            def fold(rows, steps):
                acc = np.zeros(4)
                for _ in range(steps):
                    acc = acc + np.asarray(rows)
                return acc
            """
        )
        == []
    )


# -- R2: whole-buffer re-upload of a host round trip -------------------------

R2_SRC = """
    import jax.numpy as jnp
    import numpy as np

    def exchange(fr, keep, take):
        host = np.asarray(fr.nodes)
        host[:take] = keep
        return jnp.asarray(host)
"""


def test_r2_fires_on_round_trip_reupload():
    vs = lint(R2_SRC, rules={"R2"})
    assert rules_of(vs) == ["R2"] and "at[:k].set" in vs[0].message


def test_r2_quiet_outside_hot_contexts():
    # one-time setup round trips are legitimate (e.g. _bound_setup)
    assert (
        lint(
            """
            import jax.numpy as jnp
            import numpy as np

            def setup(d):
                d64 = np.asarray(d)
                return jnp.asarray(d64)
            """,
            rules={"R2"},
        )
        == []
    )


def test_r2_quiet_on_sliced_writeback():
    assert (
        lint(
            """
            import jax.numpy as jnp
            import numpy as np

            def exchange(fr, keep, take):
                return fr.nodes.at[:take].set(jnp.asarray(keep))
            """,
            rules={"R2"},
        )
        == []
    )


# -- R3: python control flow on jitted outputs --------------------------------

R3_SRC = """
    import jax

    @jax.jit
    def step(x):
        return x * 2

    def run(x):
        y = step(x)
        if y > 0:
            return 1
        while y < 3:
            y = step(y)
        return 0
"""


def test_r3_fires_on_if_and_while():
    vs = lint(R3_SRC)
    assert [v.rule for v in vs] == ["R3", "R3"]


def test_r3_quiet_with_scalar_conversion():
    assert (
        lint(
            """
            import jax

            @jax.jit
            def step(x):
                return x * 2

            def run(x):
                y = float(step(x))
                if y > 0:
                    return 1
                z = step(x)
                if int(z) > 0:
                    return 2
                return 0
            """
        )
        == []
    )


def test_r3_tracks_jax_jit_assignment_and_unpack():
    vs = lint(
        """
        import jax

        def _kernel(x):
            return x + 1, x - 1

        kernel = jax.jit(_kernel)

        def run(x):
            hi, lo = kernel(x)
            if hi > 0:
                return lo
            return hi
        """
    )
    assert rules_of(vs) == ["R3"]


# -- R4: jnp calls in a python for loop ---------------------------------------

R4_SRC = """
    import jax.numpy as jnp

    def fold(xs):
        acc = 0.0
        for x in xs:
            acc = acc + jnp.sum(x)
        return acc
"""


def test_r4_fires_once_per_loop_anchored_on_for():
    vs = lint(R4_SRC)
    assert rules_of(vs) == ["R4"]
    assert vs[0].code.startswith("for ")


def test_r4_quiet_on_plain_python_loop():
    assert (
        lint(
            """
            def fold(xs):
                acc = 0.0
                for x in xs:
                    acc += x
                return acc
            """
        )
        == []
    )


# -- R5: early return None drops mutated self state ---------------------------

R5_SRC = """
    class Store:
        def flush(self, rows, cap):
            self.chunks = []
            merged = rows + ["extra"]
            take = min(len(merged), cap)
            if take == 0:
                return None
            self.chunks.append(merged[:take])
            return merged
"""


def test_r5_fires_on_state_dropping_return():
    vs = lint(R5_SRC)
    assert rules_of(vs) == ["R5"] and vs[0].scope == "Store.flush"


def test_r5_quiet_when_state_respilled():
    # the fixed _partition shape: write back before the early return
    assert (
        lint(
            """
            class Store:
                def flush(self, rows, cap):
                    self.chunks = []
                    merged = rows + ["extra"]
                    take = min(len(merged), cap)
                    if take == 0:
                        self.chunks.append(merged)
                        return None
                    self.chunks.append(merged[:take])
                    return merged
            """
        )
        == []
    )


# -- escape hatches ------------------------------------------------------------

def test_inline_disable_same_line_and_line_above():
    base = R4_SRC.replace(
        "for x in xs:", "for x in xs:  # graftlint: disable=R4"
    )
    assert lint(base) == []
    above = R4_SRC.replace(
        "        for x in xs:",
        "        # static unroll  # graftlint: disable=R4\n        for x in xs:",
    )
    assert lint(above) == []


def test_def_line_disable_covers_whole_function():
    src = R1_LOOP.replace(
        "def drain(fr, steps):",
        "def drain(fr, steps):  # graftlint: disable=R1",
    )
    assert lint(src) == []


def test_bare_disable_silences_all_rules():
    src = R2_SRC.replace(
        "return jnp.asarray(host)",
        "return jnp.asarray(host)  # graftlint: disable",
    )
    assert lint(src, rules={"R2"}) == []


def test_unrelated_disable_does_not_suppress():
    src = R4_SRC.replace(
        "for x in xs:", "for x in xs:  # graftlint: disable=R1"
    )
    assert rules_of(lint(src)) == ["R4"]


# -- baseline ------------------------------------------------------------------

def test_baseline_roundtrip_and_new_detection(tmp_path):
    vs = lint(R4_SRC)
    path = tmp_path / "baseline.json"
    graftlint.write_baseline(path, vs)
    res = graftlint.apply_baseline(vs, graftlint.load_baseline(path))
    assert res.new == [] and len(res.accepted) == 1 and res.stale == []

    # a second, different violation is NEW even with the baseline applied
    more = vs + lint(R5_SRC)
    res2 = graftlint.apply_baseline(more, graftlint.load_baseline(path))
    assert [v.rule for v in res2.new] == ["R5"]


def test_baseline_is_line_number_free(tmp_path):
    vs = lint(R4_SRC)
    path = tmp_path / "baseline.json"
    graftlint.write_baseline(path, vs)
    # shift the whole fixture down three lines: same fingerprint
    shifted = lint("\n\n\n" + textwrap.dedent(R4_SRC))
    assert shifted[0].line != vs[0].line
    res = graftlint.apply_baseline(shifted, graftlint.load_baseline(path))
    assert res.new == []


def test_baseline_reports_stale_entries(tmp_path):
    path = tmp_path / "baseline.json"
    graftlint.write_baseline(path, lint(R4_SRC))
    res = graftlint.apply_baseline([], graftlint.load_baseline(path))
    assert len(res.stale) == 1


# -- the CLI and the repo itself ----------------------------------------------

def test_cli_nonzero_on_each_rule_fixture(tmp_path, capsys):
    fixtures = {"R1": R1_LOOP, "R2": R2_SRC, "R3": R3_SRC, "R4": R4_SRC,
                "R5": R5_SRC}
    for rule, src in fixtures.items():
        bad = tmp_path / f"bad_{rule.lower()}.py"
        bad.write_text(textwrap.dedent(src))
        rc = graftlint_main([str(bad), "--no-baseline"])
        assert rc == 1, f"{rule} fixture must fail the lint"
        assert rule in capsys.readouterr().out


def test_cli_zero_on_clean_file(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text("import numpy as np\n\n\ndef f(x):\n    return np.sum(x)\n")
    assert graftlint_main([str(good), "--no-baseline"]) == 0
    capsys.readouterr()


def test_repo_is_clean_modulo_checked_in_baseline(capsys):
    """The regression gate: the package + tools at HEAD must lint clean
    against the checked-in baseline (exactly what `make lint` runs)."""
    assert graftlint_main([]) == 0
    out = capsys.readouterr().out
    assert "0 new" in out


# -- runtime contracts: Frontier ----------------------------------------------

def _tiny_frontier(n=6, capacity=16):
    min_out = np.ones(n, np.float64)
    return bb.make_root_frontier(n, capacity, min_out)


def test_check_frontier_accepts_engine_frontier():
    fr = _tiny_frontier()
    assert contracts.check_frontier(fr, n=6) is fr


def test_check_frontier_rejects_bad_dtype_and_width():
    fr = _tiny_frontier()
    bad_dtype = bb.Frontier(fr.nodes.astype(jnp.float32), fr.count, fr.overflow)
    with pytest.raises(contracts.ContractError, match="int32"):
        contracts.check_frontier(bad_dtype)
    bad_width = bb.Frontier(fr.nodes[:, :5], fr.count, fr.overflow)
    with pytest.raises(contracts.ContractError, match="layout"):
        contracts.check_frontier(bad_width)
    # v2 byte-packing: n is ambiguous WITHIN a path-word cell, so the
    # mismatch must be asserted against an n from a different cell
    with pytest.raises(contracts.ContractError, match="expected n="):
        contracts.check_frontier(fr, n=17)


def test_check_frontier_rejects_bad_count_shape():
    fr = _tiny_frontier()
    bad = bb.Frontier(fr.nodes, jnp.zeros(3, jnp.int32), fr.overflow)
    with pytest.raises(contracts.ContractError, match="count"):
        contracts.check_frontier(bad)


def test_check_frontier_strict_count_range(monkeypatch):
    fr = _tiny_frontier(capacity=16)
    over = bb.Frontier(fr.nodes, jnp.asarray(10_000, jnp.int32), fr.overflow)
    contracts.check_frontier(over)  # metadata-only level: passes
    monkeypatch.setenv("TSP_CONTRACTS", "strict")
    with pytest.raises(contracts.ContractError, match="outside"):
        contracts.check_frontier(over)


def test_contracts_off_disables_everything(monkeypatch):
    fr = _tiny_frontier()
    bad = bb.Frontier(fr.nodes.astype(jnp.float32), fr.count, fr.overflow)
    monkeypatch.setenv("TSP_CONTRACTS", "off")
    assert contracts.check_frontier(bad) is bad


# -- runtime contracts: PaddedTour --------------------------------------------

def test_check_padded_tour_boundaries():
    from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, make_padded

    t = make_padded([0, 1, 2, 0], 4, 10.0, capacity=8)
    assert contracts.check_padded_tour(t, capacity=8) is t
    bad_ids = PaddedTour(t.ids.astype(jnp.int64), t.length, t.cost)
    with pytest.raises(contracts.ContractError, match="int32"):
        contracts.check_padded_tour(bad_ids)
    bad_len = PaddedTour(t.ids, t.length.astype(jnp.float32), t.cost)
    with pytest.raises(contracts.ContractError, match="integer"):
        contracts.check_padded_tour(bad_len)
    with pytest.raises(contracts.ContractError, match="capacity"):
        contracts.check_padded_tour(t, capacity=16)


def test_merge_tours_contract_rejects_capacity_mismatch():
    """The boundary contract fires at trace time on malformed operands
    (batch-shape drift between ids and length)."""
    from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, merge_tours

    dist = jnp.ones((4, 4))
    t1 = PaddedTour(jnp.zeros((8,), jnp.int32), jnp.asarray(4, jnp.int32),
                    jnp.asarray(1.0))
    bad = PaddedTour(jnp.zeros((2, 8), jnp.int32), jnp.asarray(4, jnp.int32),
                     jnp.asarray(1.0))
    with pytest.raises(contracts.ContractError):
        merge_tours(t1, bad, dist)


# -- recompilation guard -------------------------------------------------------

def test_guard_passes_fixed_shape_loop():
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones(8))  # warmup compile outside the guard
    with contracts.RecompilationGuard({"f": f}, limit=0) as g:
        for _ in range(5):
            f(jnp.ones(8))
    assert g.misses() == {"f": 0}


def test_guard_fails_loop_that_rejits_every_call():
    """The acceptance case: a 'fixed-shape' hot loop that actually re-jits
    >= 2x per call (shape churn) must FAIL the guarded region."""
    f = jax.jit(lambda x: x + 1)
    f(jnp.ones(4))  # warmup
    with pytest.raises(contracts.RecompilationError, match="recompiled"):
        with contracts.RecompilationGuard({"hot_loop": f}, limit=0):
            for i in range(3):
                f(jnp.ones(5 + i))  # new shape -> new compile, every call


def test_guard_budget_allows_intentional_first_compile():
    f = jax.jit(lambda x: x - 1)
    with contracts.RecompilationGuard({"f": f}, limit=1):
        for _ in range(4):
            f(jnp.ones(16))  # one first-call compile, then cache hits


def test_guard_rejects_unjitted_callable():
    with pytest.raises(ValueError, match="_cache_size"):
        contracts.RecompilationGuard({"plain": lambda x: x})


def test_guard_on_real_engine_entry_point():
    """The tier-1 wiring the ISSUE asks for: a real fixed-shape engine jit
    (_reorder_frontier_jit) must not recompile across a steady loop.
    The entry DONATES its frontier (PR 5), so every call — the warmup
    included — must rebind to the returned one."""
    fr = _tiny_frontier(n=6, capacity=32)
    fr = bb._reorder_frontier_jit(fr, rows=32)  # warmup (donating: rebind)
    with contracts.RecompilationGuard(
        {"reorder": bb._reorder_frontier_jit}, limit=0
    ):
        for _ in range(4):
            fr = bb._reorder_frontier_jit(fr, rows=32)


def test_guard_does_not_mask_region_exception():
    f = jax.jit(lambda x: x)
    f(jnp.ones(2))
    with pytest.raises(RuntimeError, match="inner"):
        with contracts.RecompilationGuard({"f": f}, limit=0):
            f(jnp.ones(3))  # a miss the guard would flag...
            raise RuntimeError("inner")  # ...but the real error wins


# -- the ADVICE round-5 pre-fix patterns, verbatim ----------------------------

def test_r5_flags_prefix_partition_bug():
    """The literal pre-fix `_partition` shape (ADVICE r5 item 1): clear
    self.chunks, merge, then `return None` on take==0 without re-spilling
    — R5 must flag it (the repo's fixed version must NOT be flagged, which
    `test_repo_is_clean_modulo_checked_in_baseline` enforces)."""
    vs = lint(
        """
        import numpy as np

        class _Reservoir:
            def _partition(self, extra, inc_cost, capacity):
                chunks = self.chunks if extra is None else self.chunks + [extra]
                self.chunks = []
                chunks = [c for c in chunks if c.shape[0]]
                merged = np.concatenate(chunks)
                m = merged.shape[0]
                take = min(m, capacity // 2)
                if take == 0:
                    return None
                self.chunks.append(merged[take:])
                return merged[:take]
        """
    )
    assert rules_of(vs) == ["R5"]


def test_r1_r2_flag_prefix_exchange_round_trip():
    """The literal pre-fix `exchange` shape (ADVICE r5 item 3): pull the
    whole physical buffer, mutate the prefix, re-upload everything — R1
    must flag the pull and R2 the re-upload."""
    vs = lint(
        """
        import jax.numpy as jnp
        import numpy as np

        class _Reservoir:
            def exchange(self, fr, inc_cost, capacity):
                cnt = int(fr.count)
                host = np.asarray(fr.nodes).copy()
                keep = self._partition(host[:cnt], inc_cost, capacity)
                take = 0 if keep is None else keep.shape[0]
                if take:
                    host[:take] = keep
                return (jnp.asarray(host), take, fr.overflow)
        """
    )
    assert set(rules_of(vs)) == {"R1", "R2"}


def test_nested_function_code_not_attributed_to_outer_scope():
    """ast.walk pruning: a helper DEFINED inside a method/loop gets its own
    scope — its early returns must not fire R5 against the outer method,
    and jnp calls in an un-called closure must not fire R4 on the loop."""
    assert (
        lint(
            """
            class C:
                def outer(self, x):
                    self.state = []
                    cooked = x + 1

                    def helper(y):
                        z = y + 1
                        if z:
                            return None
                        return z

                    self.state.append(cooked)
                    return helper
            """
        )
        == []
    )
    assert (
        lint(
            """
            import jax.numpy as jnp

            def build(xs):
                fns = []
                for x in xs:
                    def thunk():
                        return jnp.sum(jnp.ones(3))
                    fns.append(thunk)
                return fns
            """,
            rules={"R4"},
        )
        == []
    )


def test_write_baseline_refuses_partial_surface_into_default(tmp_path, capsys):
    """--write-baseline over explicit paths must not clobber the repo-wide
    default baseline (it would drop every accepted site outside them)."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(R4_SRC))
    assert graftlint_main([str(bad), "--write-baseline"]) == 2
    assert "refusing" in capsys.readouterr().out
    # with an explicit --baseline it works fine
    out = tmp_path / "partial_baseline.json"
    assert graftlint_main([str(bad), "--write-baseline",
                           "--baseline", str(out)]) == 0
    assert graftlint_main([str(bad), "--baseline", str(out)]) == 0


def test_cli_nonexistent_path_is_usage_error(tmp_path, capsys):
    assert graftlint_main([str(tmp_path / "no_such_dir")]) == 2
    assert "no such path" in capsys.readouterr().out


def test_contract_error_is_a_value_error():
    """CLI entry points wrap kernels in `except ValueError` for a clean
    exit 2 — contract failures must flow through that path, not escape
    as raw tracebacks."""
    assert issubclass(contracts.ContractError, ValueError)


# -- PR 2: drained baseline, stale-debt detector, exchange contracts ----------

def test_baseline_is_drained_and_never_grows():
    """The ratchet: PR 2 drained the graftlint baseline to ZERO entries
    (the sharded spill_refill debt was the last). New hot-path violations
    must be fixed, not baselined — this assertion makes the invariant
    permanent."""
    from tsp_mpi_reduction_tpu.analysis.__main__ import _DEFAULT_BASELINE

    baseline = graftlint.load_baseline(_DEFAULT_BASELINE)
    assert sum(baseline.values()) == 0, (
        "graftlint_baseline.json grew again — fix the violation instead of "
        f"re-accepting debt: {sorted(baseline)}"
    )


def test_collect_scopes_qualified_names():
    import ast

    tree = ast.parse(textwrap.dedent(
        """
        def solve_sharded():
            def spill_refill():
                pass

        class _Reservoir:
            def exchange(self):
                pass
        """
    ))
    scopes = graftlint.collect_scopes(tree)
    assert {"<module>", "solve_sharded", "solve_sharded.spill_refill",
            "_Reservoir", "_Reservoir.exchange"} <= scopes
    assert "exchange" not in scopes  # only the qualified name exists


def test_find_dead_scopes_detects_gone_code(tmp_path):
    """A baseline entry whose scope vanished from the source is DEAD debt
    — it can never be repaid and must fail the gate; entries whose scope
    still exists are left alone (they may just be stale text)."""
    mod = tmp_path / "engine.py"
    mod.write_text("def keeper():\n    pass\n")
    baseline = {
        "engine.py::R1::keeper::x = 1": 1,           # scope alive
        "engine.py::R1::vanished.inner::y = 2": 1,   # scope gone
        "missing.py::R2::whatever::z = 3": 1,        # file gone
        "not-a-fingerprint": 1,                      # unparseable
    }
    dead = graftlint.find_dead_scopes(baseline, tmp_path)
    assert dead == sorted([
        "engine.py::R1::vanished.inner::y = 2",
        "missing.py::R2::whatever::z = 3",
        "not-a-fingerprint",
    ])


def test_cli_fails_on_dead_baseline_entry(tmp_path, capsys):
    """`make lint` must go red when the baseline carries debt for code
    that no longer exists (the stale-debt detector satellite)."""
    src = tmp_path / "clean.py"
    src.write_text("def f():\n    return 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(
        '{"version": 1, "entries": {"'
        + str(src) + '::R1::gone_scope::x = np.asarray(fr.nodes)": 1}}'
    )
    rc = graftlint_main([str(src), "--baseline", str(bl)])
    assert rc == 1
    assert "DEAD baseline entry" in capsys.readouterr().out


def test_fetch_live_rows_is_a_default_hot_path():
    """The one accepted transfer site must stay under lint surveillance:
    it is hot by default, so an UN-waived pull added there still fires."""
    assert "_fetch_live_rows" in graftlint.DEFAULT_HOT_PATHS
    vs = lint(
        """
        import numpy as np

        def _fetch_live_rows(fr, cnt):
            extra = np.asarray(fr.nodes)
            return np.asarray(fr.nodes[:cnt]).copy()  # graftlint: disable=R1
        """
    )
    assert rules_of(vs) == ["R1"]  # the waived line is quiet, the new pull is not


def test_check_exchange_count_bounds():
    """The sharded exchange boundary contract: kept counts outside
    [0, capacity // 2] must fail (they re-arm the overflow pressure the
    reservoir exists to shed)."""
    assert contracts.check_exchange_count(0, 1) == 0
    assert contracts.check_exchange_count(4, 8) == 4
    with pytest.raises(contracts.ContractError, match="outside"):
        contracts.check_exchange_count(5, 8)
    with pytest.raises(contracts.ContractError, match="outside"):
        contracts.check_exchange_count(-1, 8)
    with pytest.raises(contracts.ContractError, match="outside"):
        contracts.check_exchange_count(1, 1)  # capacity//2 == 0 keeps nothing


def test_check_exchange_count_off_level(monkeypatch):
    monkeypatch.setenv("TSP_CONTRACTS", "off")
    assert contracts.check_exchange_count(999, 4) == 999


# -- R6: non-atomic write of a durable artifact --------------------------------

R6_OPEN = """
import json

def publish(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
"""

R6_SAVEZ = """
import numpy as np

def snapshot(path, frontier):
    np.savez_compressed(path, nodes=frontier)
"""


def test_r6_flags_bare_open_write():
    vs = lint(R6_OPEN, rules={"R6"})
    assert rules_of(vs) == ["R6"] and "os.replace" in vs[0].message


def test_r6_flags_direct_savez():
    vs = lint(R6_SAVEZ, rules={"R6"})
    assert rules_of(vs) == ["R6"] and vs[0].scope == "snapshot"


def test_r6_quiet_on_atomic_publish_pattern():
    """os.replace anywhere in the scope marks the temp-then-rename idiom."""
    vs = lint(
        """
        import json, os

        def publish(path, obj):
            part = path + ".part"
            with open(part, "w") as f:
                json.dump(obj, f)
            os.replace(part, path)
        """,
        rules={"R6"},
    )
    assert vs == []


def test_r6_quiet_on_in_memory_buffer():
    vs = lint(
        """
        import io
        import numpy as np

        def to_bytes(arr):
            buf = io.BytesIO()
            np.savez_compressed(buf, arr=arr)
            return buf.getvalue()
        """,
        rules={"R6"},
    )
    assert vs == []


def test_r6_quiet_on_temp_paths_and_reads():
    vs = lint(
        """
        import tempfile

        def scratch(tmp_path, p):
            with open(tmp_path, "w") as f:
                f.write("x")
            with open(p) as f:
                return f.read()
        """,
        rules={"R6"},
    )
    assert vs == []


def test_r6_fires_at_module_level_and_honors_disable():
    vs = lint(
        """
        with open("results.json", "w") as f:
            f.write("{}")
        """,
        rules={"R6"},
    )
    assert rules_of(vs) == ["R6"] and vs[0].scope == "<module>"
    vs = lint(
        """
        with open("results.json", "w") as f:  # graftlint: disable=R6
            f.write("{}")
        """,
        rules={"R6"},
    )
    assert vs == []


def test_r6_mode_keyword_and_exclusive_create():
    assert rules_of(lint("f = open('out.bin', mode='wb')", rules={"R6"})) == ["R6"]
    assert rules_of(lint("f = open('out.bin', 'x')", rules={"R6"})) == ["R6"]
    assert lint("f = open('out.bin', 'rb')", rules={"R6"}) == []


def test_r6_repo_surface_is_clean():
    """The whole lint surface carries ZERO R6 debt: every durable-artifact
    writer already publishes atomically (resilience.checkpoint) or is
    explicitly waived. The baseline ratchet keeps it that way."""
    import pathlib

    from tsp_mpi_reduction_tpu.analysis.__main__ import (
        _DEFAULT_TARGETS,
        _REPO_ROOT,
    )

    vs = graftlint.lint_paths(
        [pathlib.Path(p) for p in _DEFAULT_TARGETS if pathlib.Path(p).exists()],
        root=_REPO_ROOT,
        rules={"R6"},
    )
    assert vs == [], [v.render() for v in vs]


def test_r6_temp_exemption_is_token_bounded():
    """Substring matching would exempt 'attempt'/'template'/'temperature'
    — the torn-write hazard R6 exists for. Only real temp TOKENS are."""
    flagged = """
    import numpy as np

    def sweep(state):
        for attempt in range(3):
            np.savez_compressed(f"run_{attempt}.npz", **state)
    """
    vs = lint(flagged, rules={"R6"})
    assert rules_of(vs) == ["R6"]
    assert rules_of(
        lint("f = open(template_out, 'w')", rules={"R6"})
    ) == ["R6"]
    assert rules_of(
        lint("f = open('temperature.json', 'w')", rules={"R6"})
    ) == ["R6"]
    # genuine temp tokens still exempt
    assert lint("f = open(path + '.tmp', 'wb')", rules={"R6"}) == []
    assert lint("f = open(tmp_dir + '/x', 'w')", rules={"R6"}) == []
    assert lint(
        "import tempfile\nf = open(tempfile.mkdtemp() + '/x', 'w')",
        rules={"R6"},
    ) == []


# -- R7: jit frontier entry without buffer donation ----------------------------

R7_DECORATED = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("k",))
    def expand(fr, d, k):
        return fr
"""


def test_r7_flags_partial_jit_decorator_without_donation():
    vs = lint(R7_DECORATED, rules={"R7"})
    assert rules_of(vs) == ["R7"] and "fr" in vs[0].message


def test_r7_quiet_with_donate_argnames():
    assert lint(
        R7_DECORATED.replace(
            'static_argnames=("k",)',
            'static_argnames=("k",), donate_argnames=("fr",)',
        ),
        rules={"R7"},
    ) == []


def test_r7_quiet_with_donate_argnums():
    assert lint(
        R7_DECORATED.replace(
            'static_argnames=("k",)',
            'static_argnames=("k",), donate_argnums=(0,)',
        ),
        rules={"R7"},
    ) == []


def test_r7_fused_step_entry_shape_recognized():
    """The ISSUE 8 fused-step entry: a donating jit whose body routes the
    frontier through a Pallas pallas_call with input_output_aliases. R7
    must see the donation (quiet), and the same entry WITHOUT donation
    must still fire — the Pallas aliasing is not a substitute for the
    dispatch-level donation R7 enforces."""
    fused = """
        import jax
        from functools import partial
        from jax.experimental import pallas as pl

        @partial(jax.jit, static_argnames=("k", "n", "step_kernel"),
                 donate_argnames=("fr",))
        def _expand_step(fr, inc, k, n, step_kernel="fused"):
            new_nodes = pl.pallas_call(
                _push_kernel,
                out_shape=fr.nodes,
                input_output_aliases={0: 0},
            )(fr.nodes)
            return fr._replace(nodes=new_nodes)
    """
    assert lint(fused, rules={"R7"}) == []
    undonated = fused.replace(
        ',\n                 donate_argnames=("fr",)', ''
    )
    vs = lint(undonated, rules={"R7"})
    assert rules_of(vs) == ["R7"] and "fr" in vs[0].message


def test_r7_flags_bare_jit_decorator():
    vs = lint(
        """
        import jax

        @jax.jit
        def step(fr):
            return fr
        """,
        rules={"R7"},
    )
    assert rules_of(vs) == ["R7"]


def test_r7_flags_frontier_annotation_any_param_name():
    vs = lint(
        """
        import jax

        @jax.jit
        def step(work: Frontier):
            return work
        """,
        rules={"R7"},
    )
    assert rules_of(vs) == ["R7"] and "work" in vs[0].message


def test_r7_flags_jit_assignment_of_named_function():
    vs = lint(
        """
        import jax

        def reorder(fr, rows=None):
            return fr

        reorder_jit = jax.jit(reorder, static_argnames=("rows",))
        """,
        rules={"R7"},
    )
    assert rules_of(vs) == ["R7"]


def test_r7_flags_partial_applied_assignment_and_lambda():
    vs = lint(
        """
        import jax
        from functools import partial

        def loop(fr, k):
            return fr

        loop_jit = partial(jax.jit, static_argnames=("k",))(loop)
        lam = jax.jit(lambda fr: fr)
        """,
        rules={"R7"},
    )
    assert [v.rule for v in vs] == ["R7", "R7"]


def test_r7_quiet_on_donated_assignment_and_non_frontier_params():
    assert lint(
        """
        import jax

        def reorder(fr, rows=None):
            return fr

        reorder_jit = jax.jit(
            reorder, static_argnames=("rows",), donate_argnames=("fr",)
        )
        plain = jax.jit(lambda x, y: x + y)

        @jax.jit
        def math_kernel(x, weights):
            return x @ weights
        """,
        rules={"R7"},
    ) == []


def test_r7_unresolvable_wrapper_is_skipped():
    # jit(shard_map(...)): the wrapped callable's params are invisible to
    # the AST — documented limitation, must not false-positive
    assert lint(
        """
        import jax

        step = jax.jit(shard_map(body, mesh=mesh))
        """,
        rules={"R7"},
    ) == []


def test_r7_inline_disable_on_assignment():
    assert lint(
        """
        import jax

        def loop(fr, k):
            return fr

        loop_ref = jax.jit(loop)  # graftlint: disable=R7 — harness twin
        """,
        rules={"R7"},
    ) == []


def test_r7_engine_entries_are_donating():
    """The real engine: every jit frontier entry either donates or carries
    the explicit R7 waiver — the repo-wide baseline stays at zero."""
    import pathlib

    from tsp_mpi_reduction_tpu.analysis.__main__ import (
        _DEFAULT_TARGETS,
        _REPO_ROOT,
    )

    vs = graftlint.lint_paths(
        [pathlib.Path(p) for p in _DEFAULT_TARGETS if pathlib.Path(p).exists()],
        root=_REPO_ROOT,
        rules={"R7"},
    )
    assert vs == [], [v.render() for v in vs]


# -- R8: metric/trace recording inside jit-traced code -------------------------


def test_r8_flags_registry_inc_in_jit_decorated_body():
    vs = lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        @jax.jit
        def step(x):
            REGISTRY.inc("steps_total")
            return x + 1
        """,
        rules={"R8"},
    )
    assert rules_of(vs) == ["R8"] and "trace" in vs[0].message.lower()


def test_r8_flags_jit_wrapped_assignment_callee():
    vs = lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.resilience.health import HEALTH

        def _expand(fr):
            HEALTH.incr("expansions")
            return fr

        expand = jax.jit(_expand, donate_argnums=(0,))
        """,
        rules={"R8"},
    )
    assert rules_of(vs) == ["R8"]


def test_r8_flags_scan_and_shard_map_bodies():
    vs = lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.obs import tracing

        def solver(fr):
            def body(c, x):
                tracing.add_event("boom")
                return c, x
            return jax.lax.scan(body, 0, fr)

        def collective(mesh):
            def kernel(rows):
                REGISTRY.observe("rows_seen", rows.shape[0])
                return rows
            return shard_map(kernel, mesh=mesh)
        """,
        rules={"R8"},
    )
    assert [v.rule for v in vs] == ["R8", "R8"]
    assert {v.scope for v in vs} == {"solver.body", "collective.kernel"}


def test_r8_flags_bare_span_call_in_jit_body():
    vs = lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.obs.tracing import span

        @jax.jit
        def step(x):
            with span("inner"):
                return x * 2
        """,
        rules={"R8"},
    )
    assert rules_of(vs) == ["R8"]


def test_r8_quiet_on_host_side_recording_and_jit_buffer_writes():
    assert lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def host_loop(fr):
            REGISTRY.inc("dispatches_total")   # host side: fine
            return step(fr)

        @jax.jit
        def step(fr):
            # .at[].set and estimator-style .observe on non-obs roots
            # must not false-positive
            fr = fr.at[0].set(1)
            self_estimator.observe(fr)
            return fr
        """,
        rules={"R8"},
    ) == []


def test_r8_inline_disable_honored():
    assert lint(
        """
        import jax
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        @jax.jit
        def step(x):
            REGISTRY.inc("steps_total")  # graftlint: disable=R8 — trace-time by design
            return x + 1
        """,
        rules={"R8"},
    ) == []


def test_r8_repo_is_clean():
    """The shipped telemetry layer records only around dispatches — the
    whole package lints clean under R8 with zero baseline entries."""
    import pathlib

    from tsp_mpi_reduction_tpu.analysis.__main__ import (
        _DEFAULT_TARGETS,
        _REPO_ROOT,
    )

    vs = graftlint.lint_paths(
        [pathlib.Path(p) for p in _DEFAULT_TARGETS if pathlib.Path(p).exists()],
        root=_REPO_ROOT,
        rules={"R8"},
    )
    assert vs == [], [v.render() for v in vs]


# ---------------------------------------------------------------------------
# R13: unbounded metric-label cardinality (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def test_r13_flags_fstring_label():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(user):
            REGISTRY.inc("requests_total", who=f"user-{user}")
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and "f-string" in vs[0].message


def test_r13_flags_loop_variable_label():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(items):
            for item in items:
                REGISTRY.inc("seen_total", kind=item)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and "loop variable" in vs[0].message


def test_r13_flags_per_request_field_label():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def handle(request, req):
            REGISTRY.observe("latency_seconds", 0.1, rid=request["id"])
            REGISTRY.set_gauge("g", 1.0, src=str(req.get("src")))
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and len(vs) == 2
    assert "per-request" in vs[0].message


def test_r13_loop_variable_scope_ends_with_the_loop():
    # after the loop body, the name is an ordinary local again — and a
    # nested def starts a fresh loop-target scope
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(items):
            for item in items:
                pass
            item = "fixed"
            REGISTRY.inc("seen_total", kind=item)

        def outer(rows):
            for row in rows:
                def inner():
                    REGISTRY.inc("x_total", row="literal-arg-name")
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == []


def test_r13_quiet_on_bounded_labels():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        TIER = "bnb"

        def fold(entry, outcome):
            REGISTRY.inc("outcomes_total", entry=entry, outcome=outcome)
            REGISTRY.inc("answers_total", tier=TIER)
            REGISTRY.observe("seconds", 1.5, phase="compile")
            # the variable part belongs in the VALUE, not a label
            REGISTRY.inc("bytes_total", 4096, direction="to_host")
            for seam in ("a", "b"):
                OTHER.fire(seam=seam)  # non-registry receivers exempt
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == []


def test_r13_range_loop_labels_are_bounded():
    # rank ids drawn from range(num_ranks) are bounded by construction —
    # the ISSUE 10 per-rank gauges (obs.rankview.fold_rank_view) must
    # never trip the rule; enumerate(range(...)) and a str() wrap are
    # the same set
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def fold(balance, num_ranks):
            for r in range(num_ranks):
                REGISTRY.inc("bnb_rank_nodes_total", balance[r], rank=r)
                REGISTRY.set_gauge("bnb_rank_occupancy", 1.0, rank=str(r))
            for i, _w in enumerate(range(8)):
                REGISTRY.inc("windows_total", idx=i)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == []


def test_r13_non_range_rebind_strips_the_bounded_exemption():
    # an inner loop re-binding a bounded name from an UNBOUNDED iterable
    # makes it unbounded again — inside the inner loop's body AND after
    # it (the loop var outlives the loop, holding the last request)
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def fold(requests):
            for r in range(4):
                for r in requests:
                    REGISTRY.inc("seen_total", rank=r)
                REGISTRY.inc("after_total", rank=r)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and len(vs) == 2
    assert all("loop variable" in v.message for v in vs)


def test_r13_strip_survives_inner_bounded_loop_exit():
    # a non-range rebind of 'a' nested inside ANOTHER range loop: the
    # inner range loop's exit must not resurrect 'a' as bounded (only a
    # loop's OWN targets are restored on its exit)
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def fold(reqs, na, nb):
            for a in range(na):
                for b in range(nb):
                    for a in reqs:
                        pass
                REGISTRY.inc("x_total", rank=a)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and len(vs) == 1


def test_r13_range_over_data_size_is_not_bounded():
    # range(len(requests)) / range(q.qsize()) are sized by DATA — the
    # label set grows with traffic, so the range exemption must not
    # apply (only configuration-shaped args: names/constants/attributes)
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(requests, q):
            for i in range(len(requests)):
                REGISTRY.inc("seen_total", idx=i)
            for j in range(q.qsize()):
                REGISTRY.inc("queued_total", idx=j)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"] and len(vs) == 2


def test_r13_bounded_exemption_ends_with_the_range_loop():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def fold(items):
            for r in range(4):
                pass
            for r in items:
                REGISTRY.inc("seen_total", rank=r)
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == ["R13"]


def test_r13_value_kwarg_is_not_a_label():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(req):
            REGISTRY.inc("elapsed_total", value=req["elapsed_ms"])
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == []


def test_r13_inline_disable_honored():
    vs = lint(
        """
        from tsp_mpi_reduction_tpu.obs.metrics import REGISTRY

        def record(request):
            REGISTRY.inc("x_total", rid=request["id"])  # graftlint: disable=R13
        """,
        rules={"R13"},
    )
    assert rules_of(vs) == []


def test_r13_repo_is_clean():
    """Every registry call site in the shipped package labels from fixed
    sets (tier/entry/seam/phase names) — R13 lints clean at zero
    baseline entries."""
    import pathlib

    from tsp_mpi_reduction_tpu.analysis.__main__ import (
        _DEFAULT_TARGETS,
        _REPO_ROOT,
    )

    vs = graftlint.lint_paths(
        [pathlib.Path(p) for p in _DEFAULT_TARGETS if pathlib.Path(p).exists()],
        root=_REPO_ROOT,
        rules={"R13"},
    )
    assert vs == [], [v.render() for v in vs]

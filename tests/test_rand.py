"""glibc-rand replica vs the committed golden stream and the live libc."""

import ctypes
import ctypes.util
import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.rand import GlibcRand


def test_matches_golden_stream(goldens_dir):
    golden = json.loads((goldens_dir / "glibc_rand_seed0.json").read_text())
    rng = GlibcRand(golden["seed"])
    got = rng.fill(len(golden["values"]))
    np.testing.assert_array_equal(got, np.asarray(golden["values"]))


def test_next_and_fill_agree():
    a, b = GlibcRand(0), GlibcRand(0)
    assert [a.next() for _ in range(100)] == b.fill(100).tolist()


@pytest.mark.parametrize("seed", [0, 1, 2, 42, 123456789, 2**31 - 1, 2**32 - 1])
def test_matches_live_libc(seed):
    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
    libc.srand(ctypes.c_uint(seed))
    ours = GlibcRand(seed)
    for _ in range(500):
        assert libc.rand() == ours.next()


def test_seed_zero_equals_seed_one():
    # glibc maps seed 0 to 1 (stdlib/random_r.c); the reference uses srand(0)
    assert GlibcRand(0).fill(50).tolist() == GlibcRand(1).fill(50).tolist()

"""Profiling utilities: phase timers and jax.profiler trace capture."""

import threading
import time

import jax.numpy as jnp

from tsp_mpi_reduction_tpu.utils.profiling import PhaseTimer, device_trace


def test_phase_timer_accumulates_across_reentry():
    t = PhaseTimer()
    for _ in range(3):
        with t.phase("work"):
            pass
    with t.phase("other"):
        pass
    assert set(t.seconds) == {"work", "other"}
    assert t.seconds["work"] >= 0.0


def test_phase_timer_records_on_exception():
    t = PhaseTimer()
    try:
        with t.phase("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert "boom" in t.seconds


def test_phase_timer_thread_safe_merge():
    """The serve scheduler's worker thread and request threads share one
    timer: concurrent merges into the same phase must not lose updates
    (the unlocked read-modify-write raced before ISSUE 3)."""
    t = PhaseTimer()
    rounds, threads = 200, 8
    sleep_s = 1e-5

    def hammer():
        for _ in range(rounds):
            with t.phase("shared"):
                time.sleep(sleep_s)
            with t.phase("shared2"):
                pass

    workers = [threading.Thread(target=hammer) for _ in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    # every merge must land: the accumulated total is at least the sum of
    # all sleeps (a lost update would undercount)
    assert t.seconds["shared"] >= rounds * threads * sleep_s
    assert set(t.seconds) == {"shared", "shared2"}


def test_device_trace_none_is_noop():
    with device_trace(None):
        assert float(jnp.zeros(2).sum()) == 0.0


def test_device_trace_writes_profile(tmp_path):
    d = str(tmp_path / "trace")
    with device_trace(d):
        jnp.arange(8.0).sum().block_until_ready()
    files = list((tmp_path / "trace").rglob("*"))
    assert files, "profiler trace directory is empty"

"""Bit-exact parity of the Pallas Prim chain vs the jnp reference.

The kernel (ops/prim_pallas.prim_chain) must produce IDENTICAL (tot,
deg) to the fori-loop in models/branch_bound._mst_conn — the bound it
feeds certifies pruning, so even 1-ulp drift would change search
trajectories. On CPU the kernel runs in interpret mode (same program
semantics as the Mosaic-compiled TPU path).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops.prim_pallas import prim_chain


def _compare_kernels(dbar, unvis, n, lam=None):
    """Assert the registry contract: the Pallas chain's (value, degrees)
    must be BIT-identical to _mst_conn's (the conn edges are the same
    shared jnp code, so this pins the Prim chain itself; comparing
    `val - conn` instead would manufacture inf-inf NaNs on empty-U
    lanes). interpret=True is forced so the comparison holds on any
    backend — COMPILED Mosaic argmin may break MST ties differently
    (equal value, different degrees; see the module docstring of
    ops/prim_pallas)."""
    cur = jnp.zeros(unvis.shape[0], jnp.int32)
    ref_val, ref_deg = bb._mst_conn(dbar, unvis, cur, n, lam)
    tot, deg_l = prim_chain(dbar, unvis, n, lam, interpret=True)
    conn, bump = bb._conn_edges(dbar, unvis, cur, n, lam)
    val, deg = tot + conn, deg_l + bump
    assert np.array_equal(
        np.asarray(val).view(np.int32), np.asarray(ref_val).view(np.int32)
    ), "MST+conn values must be BIT-identical"
    assert np.array_equal(np.asarray(deg), np.asarray(ref_deg))


def _random_case(rng, k, n, integral=True, frac_unvis=0.6):
    if integral:
        d = rng.integers(1, 500, size=(n, n)).astype(np.float32)
    else:
        d = (rng.random((n, n)) * 500).astype(np.float32)
    d = d + d.T
    np.fill_diagonal(d, 0.0)
    pi = (rng.integers(-20, 20, size=n)).astype(np.float32)
    dbar = d + pi[None, :] + pi[:, None]
    unvis = rng.random((k, n)) < frac_unvis
    unvis[:, 0] = False  # city 0 is never in U
    return jnp.asarray(dbar), jnp.asarray(unvis)


@pytest.mark.parametrize("n", [5, 14, 51, 100, 130, 200])
def test_prim_chain_matches_reference(n):
    rng = np.random.default_rng(n)
    k = 37  # deliberately not a ROW_TILE multiple (tests the pad path)
    dbar, unvis = _random_case(rng, k, n)
    _compare_kernels(dbar, unvis, n)


def test_prim_chain_matches_reference_noninteger_metric():
    rng = np.random.default_rng(11)
    k, n = 37, 51
    dbar, unvis = _random_case(rng, k, n, integral=False)
    _compare_kernels(dbar, unvis, n)


def test_prim_chain_matches_reference_with_lam():
    rng = np.random.default_rng(7)
    k, n = 64, 51
    dbar, unvis = _random_case(rng, k, n)
    lam = jnp.asarray(
        (rng.integers(-8, 8, size=(k, n))).astype(np.float32)
    )
    _compare_kernels(dbar, unvis, n, lam)


def test_prim_chain_degenerate_lanes():
    # lanes with 0 or 1 unvisited vertices: no MST edges can be added
    # after the start vertex; the empty-U lane's value is +inf in both
    rng = np.random.default_rng(3)
    n = 14
    dbar, _ = _random_case(rng, 4, n)
    unvis = np.zeros((4, n), bool)
    unvis[1, 3] = True  # exactly one unvisited
    unvis[2, 3:6] = True
    _compare_kernels(dbar, jnp.asarray(unvis), n)


def test_registry_kernel_proves_burma14():
    from tsp_mpi_reduction_tpu.utils import tsplib

    d = tsplib.embedded("burma14").distance_matrix()
    r = bb.solve(d, capacity=1 << 14, k=64, max_iters=100_000,
                 mst_kernel="prim_pallas")
    assert r.proven_optimal and r.cost == 3323.0


@pytest.mark.skipif(
    jax.default_backend() == "tpu",
    reason="compiled Mosaic argmin breaks MST ties differently; "
    "trajectory equality only holds in interpret mode (CPU)",
)
def test_registry_kernel_search_trajectory_matches_prim():
    # a real (non-root-closing) search must expand the SAME node count
    # under either kernel — the bound values are bit-identical
    from tsp_mpi_reduction_tpu.utils import tsplib

    d = tsplib.embedded("ulysses16").distance_matrix()
    # weaken the setup so a real search happens: min-out bound, no ILS
    r1 = bb.solve(d, capacity=1 << 14, k=32, max_iters=3000,
                  bound="min-out", ils_rounds=0, mst_kernel="prim")
    r2 = bb.solve(d, capacity=1 << 14, k=32, max_iters=3000,
                  bound="min-out", ils_rounds=0, mst_kernel="prim_pallas")
    assert r1.nodes_expanded == r2.nodes_expanded
    assert r1.cost == r2.cost

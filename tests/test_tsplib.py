"""TSPLIB parser + metrics + embedded burma14 fixture."""

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.utils import tsplib


def test_burma14_fixture_self_validates():
    inst = tsplib.burma14()
    assert inst.dimension == 14 and inst.edge_weight_type == "GEO"
    d = inst.distance_matrix()
    assert d.shape == (14, 14) and (d == d.T).all()
    # the optimum is re-derived exactly, not assumed
    costs, _ = solve_blocks_from_dists(d[None].astype(np.float64))
    assert float(costs[0]) == inst.known_optimum == 3323


def test_euc2d_parse_and_metric():
    text = """NAME: toy
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EUC_2D
NODE_COORD_SECTION
1 0.0 0.0
2 3.0 4.0
3 0.0 10.5
EOF
"""
    inst = tsplib.parse(text)
    d = inst.distance_matrix()
    assert d[0, 1] == 5  # nint(5.0)
    assert d[0, 2] == 11  # nint(10.5) = floor(11.0)


def test_explicit_full_matrix():
    text = """NAME: m3
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EXPLICIT
EDGE_WEIGHT_FORMAT: FULL_MATRIX
EDGE_WEIGHT_SECTION
0 2 3
2 0 4
3 4 0
EOF
"""
    d = tsplib.parse(text).distance_matrix()
    assert d.tolist() == [[0, 2, 3], [2, 0, 4], [3, 4, 0]]


def test_explicit_upper_row():
    text = """NAME: u3
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EXPLICIT
EDGE_WEIGHT_FORMAT: UPPER_ROW
EDGE_WEIGHT_SECTION
2 3
4
EOF
"""
    d = tsplib.parse(text).distance_matrix()
    assert d.tolist() == [[0, 2, 3], [2, 0, 4], [3, 4, 0]]


def test_att_metric():
    c = np.array([[0.0, 0.0], [10.0, 0.0]])
    d = tsplib._att(c)
    # r = sqrt(100/10) = 3.162..; nint -> 3 < r -> 4
    assert d[0, 1] == 4


def test_ceil_metric():
    c = np.array([[0.0, 0.0], [3.0, 4.1]])
    d = tsplib._ceil_2d(c)
    assert d[0, 1] == 6  # ceil(5.08..)

"""TSPLIB parser + metrics + embedded burma14 fixture."""

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.utils import tsplib


def test_burma14_fixture_self_validates():
    inst = tsplib.burma14()
    assert inst.dimension == 14 and inst.edge_weight_type == "GEO"
    d = inst.distance_matrix()
    assert d.shape == (14, 14) and (d == d.T).all()
    # the optimum is re-derived exactly, not assumed
    costs, _ = solve_blocks_from_dists(d[None].astype(np.float64))
    assert float(costs[0]) == inst.known_optimum == 3323


def test_euc2d_parse_and_metric():
    text = """NAME: toy
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EUC_2D
NODE_COORD_SECTION
1 0.0 0.0
2 3.0 4.0
3 0.0 10.5
EOF
"""
    inst = tsplib.parse(text)
    d = inst.distance_matrix()
    assert d[0, 1] == 5  # nint(5.0)
    assert d[0, 2] == 11  # nint(10.5) = floor(11.0)


def test_explicit_full_matrix():
    text = """NAME: m3
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EXPLICIT
EDGE_WEIGHT_FORMAT: FULL_MATRIX
EDGE_WEIGHT_SECTION
0 2 3
2 0 4
3 4 0
EOF
"""
    d = tsplib.parse(text).distance_matrix()
    assert d.tolist() == [[0, 2, 3], [2, 0, 4], [3, 4, 0]]


def test_explicit_upper_row():
    text = """NAME: u3
TYPE: TSP
DIMENSION: 3
EDGE_WEIGHT_TYPE: EXPLICIT
EDGE_WEIGHT_FORMAT: UPPER_ROW
EDGE_WEIGHT_SECTION
2 3
4
EOF
"""
    d = tsplib.parse(text).distance_matrix()
    assert d.tolist() == [[0, 2, 3], [2, 0, 4], [3, 4, 0]]


def test_att_metric():
    c = np.array([[0.0, 0.0], [10.0, 0.0]])
    d = tsplib._att(c)
    # r = sqrt(100/10) = 3.162..; nint -> 3 < r -> 4
    assert d[0, 1] == 4


def test_ceil_metric():
    c = np.array([[0.0, 0.0], [3.0, 4.1]])
    d = tsplib._ceil_2d(c)
    assert d[0, 1] == 6  # ceil(5.08..)


# --- embedded fixture validation (utils.tsplib_data) ---
# The coordinates were embedded from public knowledge in a zero-egress
# environment; these tests are what makes them trustworthy. Wrong data
# could not produce a Held-Karp bound AND a local-search tour that both
# land exactly on the published optimum.


def test_embedded_registry_complete():
    for name in ("burma14", "ulysses16", "ulysses22", "eil51", "berlin52", "kroA100"):
        inst = tsplib.embedded(name)
        assert inst.name == name
        assert inst.dimension == inst.distance_matrix().shape[0]
        assert inst.known_optimum is not None
    with pytest.raises(KeyError):
        tsplib.embedded("pr124")  # deliberately not embedded (see tsplib_data)


@pytest.mark.parametrize("name", ["ulysses16", "ulysses22", "berlin52"])
def test_embedded_root_bound_equals_published_optimum(name):
    """For these instances the Held-Karp 1-tree bound is EXACTLY the
    published optimum — the strongest possible data check short of a full
    proof (which tests/test_bnb.py + the recorded runs provide)."""
    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    inst = tsplib.embedded(name)
    bd = bb._bound_setup(inst.distance_matrix(), "one-tree")
    assert bd.root_lb == inst.known_optimum


@pytest.mark.parametrize("name,lb_floor", [("eil51", 420), ("kroA100", 20800)])
def test_embedded_bound_brackets_published_optimum(name, lb_floor):
    import numpy as np

    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    inst = tsplib.embedded(name)
    d = inst.distance_matrix()
    bd = bb._bound_setup(d, "one-tree")
    tour = bb.strong_incumbent(d, starts=16)
    ub = bb.tour_cost(np.asarray(d, np.float64), tour)
    assert lb_floor <= bd.root_lb <= inst.known_optimum <= ub


@pytest.mark.slow
def test_ulysses22_proven_optimal():
    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    inst = tsplib.embedded("ulysses22")
    res = bb.solve(inst.distance_matrix(), capacity=1 << 15, k=128)
    assert res.proven_optimal and res.cost == 7013.0


@pytest.mark.slow
def test_berlin52_proven_optimal():
    """The north-star acceptance instance: identical optimal tour cost."""
    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    inst = tsplib.embedded("berlin52")
    res = bb.solve(inst.distance_matrix(), capacity=1 << 17, k=256,
                   time_limit_s=300)
    assert res.proven_optimal and res.cost == 7542.0
    assert sorted(res.tour[:-1].tolist()) == list(range(52))

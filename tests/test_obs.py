"""Unified telemetry layer (ISSUE 6): registry, tracing, time-series.

Covers the metrics registry semantics (labels, kinds, snapshot/delta,
Prometheus exposition, HTTP endpoint), span-tree reconstruction from a
real multi-request serve run (no orphan spans, degraded paths included),
the chaos-suite guarantee that injected faults surface as span events
with matching trace IDs, the per-dispatch sampler (ring semantics + B&B
integration), and golden-schema tests for the two stats surfaces
(``service_stats_json`` and the ``bnb_solve.py`` payload) with counter
monotonicity.
"""

from __future__ import annotations

import importlib.util
import io
import json
import pathlib
import urllib.request

import numpy as np
import pytest

from tsp_mpi_reduction_tpu import obs
from tsp_mpi_reduction_tpu.obs import metrics, timeseries, tracing
from tsp_mpi_reduction_tpu.obs.metrics import MetricsRegistry
from tsp_mpi_reduction_tpu.resilience import faults
from tsp_mpi_reduction_tpu.resilience.health import HEALTH

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts with tracing unconfigured and the env override
    cleared, and leaves them that way."""
    tracing.configure(None)
    obs.set_enabled(None)
    yield
    tracing.configure(None)
    obs.set_enabled(None)


# -- metrics registry ----------------------------------------------------------


def test_counter_labels_and_value():
    reg = MetricsRegistry()
    reg.inc("req_total", 1, tier="bnb")
    reg.inc("req_total", 2, tier="bnb")
    reg.inc("req_total", 5, tier="greedy")
    assert reg.value("req_total", tier="bnb") == 3
    assert reg.value("req_total", tier="greedy") == 5
    assert reg.value("req_total", tier="nope") == 0
    assert reg.value("missing_total") == 0


def test_counter_rejects_negative_and_kind_flip():
    reg = MetricsRegistry()
    reg.inc("a_total")
    with pytest.raises(ValueError):
        reg.inc("a_total", -1)
    with pytest.raises(ValueError):
        reg.set_gauge("a_total", 5)  # counter name reused as gauge
    with pytest.raises(ValueError):
        reg.observe("a_total", 0.1)


def test_gauge_sets_not_accumulates():
    reg = MetricsRegistry()
    reg.set_gauge("depth", 7)
    reg.set_gauge("depth", 3)
    assert reg.value("depth") == 3


def test_histogram_buckets_sum_count():
    reg = MetricsRegistry()
    reg.declare("lat_seconds", "histogram", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        reg.observe("lat_seconds", v)
    snap = reg.snapshot()
    h = snap.data["lat_seconds"]["series"][()]
    assert h["counts"] == [1, 1, 1] and h["count"] == 3
    assert h["sum"] == pytest.approx(5.55)


def test_snapshot_delta_counters_subtract_gauges_current():
    reg = MetricsRegistry()
    reg.inc("c_total", 10)
    reg.set_gauge("g", 1)
    base = reg.snapshot()
    reg.inc("c_total", 4)
    reg.set_gauge("g", 9)
    d = reg.delta(base)
    assert d.value("c_total") == 4
    assert d.value("g") == 9  # gauges report current, not a difference


def test_counters_monotone_across_snapshots():
    reg = MetricsRegistry()
    reg.inc("m_total", 2, k="a")
    s1 = reg.snapshot()
    reg.inc("m_total", 1, k="a")
    reg.inc("m_total", 7, k="b")
    s2 = reg.snapshot()
    for key, v in s1.series("m_total").items():
        assert s2.series("m_total")[key] >= v


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.declare("req_total", "counter", help="requests served")
    reg.inc("req_total", 3, tier="bnb")
    reg.declare("lat_seconds", "histogram", buckets=(0.5,))
    reg.observe("lat_seconds", 0.2)
    text = metrics.to_prometheus(reg.snapshot())
    assert "# HELP req_total requests served" in text
    assert "# TYPE req_total counter" in text
    assert 'req_total{tier="bnb"} 3' in text
    assert 'lat_seconds_bucket{le="0.5"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text


def test_reset_for_testing_prefix_scoped():
    reg = MetricsRegistry()
    reg.inc("health_x_total", 5)
    reg.inc("other_total", 2)
    reg.reset_for_testing(prefix="health_")
    assert reg.value("health_x_total") == 0
    assert reg.value("other_total") == 2


def test_metrics_http_endpoint():
    metrics.REGISTRY.inc("http_probe_total", 1, who="test")
    server = metrics.serve_metrics_http(0)
    try:
        port = server.server_address[1]
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert 'http_probe_total{who="test"}' in text
        blob = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=5
            ).read()
        )
        assert blob["http_probe_total"]["kind"] == "counter"
    finally:
        server.shutdown()


# -- health view ---------------------------------------------------------------


def test_health_snapshot_standard_zeros_and_counts():
    snap = HEALTH.snapshot()
    for k in ("worker_restarts", "stuck_restarts", "retries",
              "fallback_restores"):
        assert snap[k] == 0  # conftest reset gives every test a boundary
    HEALTH.incr("retries", 2)
    HEALTH.incr("custom_event")
    HEALTH.incr_fault("cache.get")
    snap = HEALTH.snapshot()
    assert snap["retries"] == 2 and snap["custom_event"] == 1
    assert snap["faults_injected"] == {"cache.get": 1}
    assert HEALTH.get("retries") == 2


def test_health_delta_since_isolates_sessions():
    HEALTH.incr("retries", 3)
    HEALTH.incr_fault("cache.get")
    baseline = HEALTH.snapshot()
    HEALTH.incr("retries", 2)
    HEALTH.incr_fault("cache.get")
    HEALTH.incr_fault("ckpt.read")
    d = HEALTH.delta_since(baseline)
    assert d["retries"] == 2
    assert d["faults_injected"] == {"cache.get": 1, "ckpt.read": 1}
    # the pre-baseline counts never leak into the delta
    assert d["worker_restarts"] == 0


# -- compile-cache entry attribution ------------------------------------------


def test_compile_cache_mirrors_entry_labels():
    from tsp_mpi_reduction_tpu.perf import compile_cache as pc

    reg = metrics.REGISTRY
    before = reg.value(
        "compile_cache_outcomes_total", entry="obs_test_entry", outcome="miss"
    )
    paid0 = reg.value(
        "compile_seconds_total", entry="obs_test_entry", kind="paid"
    )
    pc.STATS.record("obs_test_entry", "miss", 1.5)
    assert reg.value(
        "compile_cache_outcomes_total", entry="obs_test_entry", outcome="miss"
    ) == before + 1
    assert reg.value(
        "compile_seconds_total", entry="obs_test_entry", kind="paid"
    ) == pytest.approx(paid0 + 1.5)


def test_compile_phase_seconds_attributes_per_entry():
    import jax
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.perf import compile_cache as pc

    fn = jax.jit(lambda x: x + 1)
    pc._compile_entry(
        fn, (jnp.zeros(3, jnp.float32),), {},
        timer_name="compile.obs_phase_entry",
    )
    phases = pc.compile_phase_seconds()
    assert "obs_phase_entry" in phases
    assert phases["obs_phase_entry"]["compile"] > 0


# -- tracing -------------------------------------------------------------------


def test_span_disabled_is_null_and_free():
    with tracing.span("x") as sp:
        sp.set("a", 1)  # swallowed, not an error
        sp.event("e")
    assert tracing.current_context() is None


def test_span_tree_nesting_and_events(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tracing.configure(path)
    with tracing.span("root", kind="test") as root:
        with tracing.span("child") as child:
            tracing.add_event("ping", n=1)
            assert child.parent_id == root.span_id
            assert child.trace_id == root.trace_id
    tracing.configure(None)
    spans = tracing.read_trace(path)
    assert [s["name"] for s in spans] == ["child", "root"]  # emit at END
    trees = tracing.build_trees(spans)
    (tree,) = trees.values()
    assert not tree["orphans"] and len(tree["roots"]) == 1
    child_rec = tree["roots"][0]["children"][0]["span"]
    assert child_rec["events"][0]["name"] == "ping"
    assert child_rec["events"][0]["attrs"] == {"n": 1}


def test_span_closes_on_exception_with_error_attr(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tracing.configure(path)
    with pytest.raises(RuntimeError):
        with tracing.span("boom"):
            raise RuntimeError("kapow")
    tracing.configure(None)
    (rec,) = tracing.read_trace(path)
    assert "kapow" in rec["attrs"]["error"]


def test_emit_span_parents_cross_thread_context(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tracing.configure(path)
    with tracing.span("request") as sp:
        ctx = tracing.current_context()
        assert ctx == (sp.trace_id, sp.span_id)
    fctx = tracing.emit_span("flush", ctx, 0.0, 0.001, {"k": 1})
    tracing.emit_span("dispatch", fctx, 0.0, 0.0005)
    tracing.configure(None)
    spans = tracing.read_trace(path)
    assert not tracing.orphan_spans(spans)
    by_name = {s["name"]: s for s in spans}
    assert by_name["flush"]["parent_id"] == by_name["request"]["span_id"]
    assert by_name["dispatch"]["parent_id"] == by_name["flush"]["span_id"]


def test_orphan_detection():
    spans = [
        {"type": "span", "trace_id": "t", "span_id": "a", "parent_id": None,
         "name": "root", "ts": 0.0, "dur_ms": 1, "attrs": {}, "events": []},
        {"type": "span", "trace_id": "t", "span_id": "b",
         "parent_id": "missing", "name": "lost", "ts": 0.0, "dur_ms": 1,
         "attrs": {}, "events": []},
    ]
    assert [s["name"] for s in tracing.orphan_spans(spans)] == ["lost"]


# -- per-dispatch sampler ------------------------------------------------------


def test_sampler_ring_keeps_newest():
    s = timeseries.StepSampler(capacity=4)
    for i in range(10):
        s.sample(step=i, wall_s=i * 0.1, nodes=1, nodes_per_s=10.0,
                 frontier=5, incumbent=100.0, lb_floor=90.0)
    out = s.series()
    assert out["samples_total"] == 10 and out["samples_dropped"] == 6
    assert [r[0] for r in out["rows"]] == [6, 7, 8, 9]  # oldest-first tail
    assert out["columns"][0] == "step"


def test_sampler_nonfinite_values_become_null():
    s = timeseries.StepSampler(capacity=2)
    s.sample(step=0, wall_s=0.0, nodes=0, nodes_per_s=0.0, frontier=1)
    (row,) = s.series()["rows"]
    assert row[7] is None and row[8] is None  # inf incumbent / -inf floor
    json.dumps(s.series())  # strict-JSON encodable


def test_sampler_maybe_respects_tsp_obs_off():
    obs.set_enabled(False)
    assert timeseries.StepSampler.maybe() is None
    obs.set_enabled(True)
    assert timeseries.StepSampler.maybe() is not None


# -- B&B integration -----------------------------------------------------------


def _tiny_solve(**over):
    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np

    rng = np.random.default_rng(5)
    d = distance_matrix_np(rng.random((9, 2)) * 100)
    kw = dict(capacity=256, k=8, inner_steps=4, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False)
    kw.update(over)
    return bb.solve(d, **kw)


def test_solve_series_present_and_coherent():
    reg = metrics.REGISTRY
    nodes0 = reg.value("bnb_nodes_expanded_total")
    res = _tiny_solve()
    assert res.proven_optimal
    assert res.series is not None
    cols, rows = res.series["columns"], res.series["rows"]
    assert cols == list(timeseries.COLUMNS)
    assert rows, "sampler recorded nothing"
    steps = [r[cols.index("step")] for r in rows]
    assert steps == sorted(steps)  # monotone step axis
    assert sum(r[cols.index("nodes")] for r in rows) <= res.nodes_expanded + 1
    # final incumbent matches the solve result
    assert rows[-1][cols.index("incumbent")] == pytest.approx(res.cost)
    # registry fold happened exactly once with the solve's totals
    assert reg.value("bnb_nodes_expanded_total") == nodes0 + res.nodes_expanded


def test_solve_series_off_under_tsp_obs_off():
    obs.set_enabled(False)
    res = _tiny_solve()
    assert res.proven_optimal and res.series is None


# -- golden schemas ------------------------------------------------------------

SERVICE_STATS_SCHEMA = {
    "responses": int, "errors": int, "deadline_misses": int,
    "refreshes": int, "rung_failures": dict, "tiers": dict, "cache": dict,
    "scheduler": dict, "phases_s": dict, "health": dict,
    "compile_cache": dict, "slo": dict, "admission": dict, "obs": dict,
}

BNB_PAYLOAD_SCHEMA = {
    "instance": str, "dimension": int, "cost": float, "proven_optimal": bool,
    "nodes_expanded": int, "nodes_per_sec": float, "time_to_best_s": float,
    "wall_s": float, "setup_s": float, "setup_ascent_s": float,
    "setup_ils_s": float, "ranks": int, "bound": str, "mst_kernel": str,
    "step_kernel": str, "push_order": str, "push_block": int,
    "root_lower_bound": float,
    "lower_bound": float, "lb_certified": float, "spill_rounds": int,
    "spill_events": int, "spill_full_merges": int, "spill_bytes_to_host": int,
    "spill_bytes_to_device": int, "health": dict, "compile_cache": dict,
    "series": dict, "anomalies": dict, "obs": dict,
}


def _serve_session(n_requests=6, tracing_path=None, deadline_ms=2500.0):
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    if tracing_path:
        tracing.configure(tracing_path)
    rng = np.random.default_rng(3)
    lines = [
        json.dumps({
            "id": f"r{i}",
            "xy": (rng.random((8, 2)) * 50).tolist(),
            "deadline_ms": deadline_ms,
        })
        for i in range(n_requests)
    ]
    out = io.StringIO()
    svc = run_jsonl(lines, out, ServiceConfig(threads=4, max_wait_ms=1.0))
    if tracing_path:
        tracing.configure(None)
    return svc, out.getvalue().strip().splitlines()


@pytest.mark.serve
def test_service_stats_json_golden_schema_and_monotonicity():
    svc, lines = _serve_session(6)
    assert len(lines) == 6
    stats = json.loads(svc.stats_json())
    assert set(stats) == set(SERVICE_STATS_SCHEMA)
    for key, typ in SERVICE_STATS_SCHEMA.items():
        assert isinstance(stats[key], typ), (key, type(stats[key]))
    assert stats["responses"] == 6 and stats["errors"] == 0
    assert 0.0 <= stats["cache"]["hit_rate"] <= 1.0
    assert isinstance(stats["obs"]["enabled"], bool)
    assert isinstance(stats["obs"]["compile_phases_s"], dict)
    # counter monotonicity: more traffic through the SAME service can
    # only grow the counting fields
    stats2 = json.loads(_serve_session(4, deadline_ms=2500.0,
                                       tracing_path=None)[0].stats_json())
    del stats2  # independent session; monotonicity is within one service
    svc2, _ = _serve_session(3)
    s_a = json.loads(svc2.stats_json())
    s_b = json.loads(svc2.stats_json())
    for key in ("responses", "errors", "deadline_misses", "refreshes"):
        assert s_b[key] >= s_a[key]
    for tier, count in s_a["tiers"].items():
        assert s_b["tiers"][tier] >= count
    for k in ("hits", "misses", "evictions"):
        assert s_b["cache"][k] >= s_a["cache"][k]


def test_bnb_solve_payload_golden_schema():
    spec = importlib.util.spec_from_file_location(
        "bnb_solve", REPO / "tools" / "bnb_solve.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.resolve_instance("random:9:5")
    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    res = bb.solve(inst.distance_matrix(), capacity=256, k=8, inner_steps=4,
                   bound="min-out", mst_prune=False, node_ascent=0,
                   device_loop=False)

    class Args:
        ranks = 1
        bound = "min-out"
        mst_kernel = "prim"
        step_kernel = "reference"
        push_order = "best-first"
        push_block = 0
        balance = "pair"

    payload = mod.result_payload(res, inst, Args())
    for key, typ in BNB_PAYLOAD_SCHEMA.items():
        assert key in payload, key
        assert isinstance(payload[key], typ), (key, type(payload[key]))
    json.dumps(payload)  # the driver's contract: one encodable JSON line
    assert payload["series"]["columns"] == list(timeseries.COLUMNS)
    # packed-row provenance rides the series (spill bytes / row_bytes =
    # rows moved; v2 = int8-packed path layout)
    assert payload["series"]["row_bytes"] == res.series["row_bytes"]
    assert payload["series"]["frontier_layout"] >= 2
    assert payload["obs"]["enabled"] is True
    assert payload["balance"] is None  # single-rank runs report no scheme
    # rank-resolved telemetry (ISSUE 10) is a sharded-solve artifact:
    # single-rank payloads carry the keys with null values (obs_report
    # --ranks errors loudly on such a payload instead of rendering an
    # empty section; the sharded golden lives in test_rankview.py)
    assert "rank_series" in payload and payload["rank_series"] is None
    assert "rank_balance" in payload["obs"]
    assert payload["obs"]["rank_balance"] is None


# -- span-tree completeness over a real serve session --------------------------

EXPECTED_REQUEST_STAGES = {"canonicalize", "cache.lookup", "respond"}


@pytest.mark.serve
def test_serve_trace_reconstructs_complete_trees(tmp_path):
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    path = str(tmp_path / "serve.jsonl")
    tracing.configure(path)
    rng = np.random.default_rng(11)
    lines = []
    for i in range(8):
        req = {"id": f"r{i}", "xy": (rng.random((8, 2)) * 50).tolist(),
               "deadline_ms": 2500.0}
        if i == 2:
            req["deadline_ms"] = 0.001  # degraded greedy path
        if i == 5:
            req["xy"] = "garbage"  # malformed: error response, traced too
        lines.append(json.dumps(req))
    out = io.StringIO()
    run_jsonl(lines, out, ServiceConfig(threads=4, max_wait_ms=1.0))
    tracing.configure(None)

    assert len(out.getvalue().strip().splitlines()) == 8
    spans = tracing.read_trace(path)
    assert tracing.orphan_spans(spans) == []  # the acceptance criterion
    trees = tracing.build_trees(spans)
    roots = [n for t in trees.values() for n in t["roots"]]
    assert len(roots) == 8
    assert all(r["span"]["name"] == "serve.request" for r in roots)
    ids = {r["span"]["attrs"]["id"] for r in roots}
    assert ids == {f"r{i}" for i in range(8)}
    for r in roots:
        child_names = {c["span"]["name"] for c in r["children"]}
        rid = r["span"]["attrs"]["id"]
        if rid == "r5":  # malformed: fails in canonicalize, still closes
            assert "error" in r["span"]["attrs"]
            continue
        assert EXPECTED_REQUEST_STAGES <= child_names, (rid, child_names)
        assert "ladder.rung" in child_names or "cache.lookup" in child_names
    # the degraded request answered greedy and its rung span says so
    r2 = next(r for r in roots if r["span"]["attrs"]["id"] == "r2")
    rungs = [c["span"] for c in r2["children"]
             if c["span"]["name"] == "ladder.rung"]
    assert rungs and rungs[-1]["attrs"]["tier"] == "greedy"
    # at least one pipeline request shows the full queue-wait -> flush ->
    # device-dispatch chain under its rung
    flush_spans = [s for s in spans if s["name"] == "sched.flush"]
    assert flush_spans, "no flush spans — scheduler path untraced"
    dispatch_spans = [s for s in spans if s["name"] == "device.dispatch"]
    assert dispatch_spans


@pytest.mark.chaos
def test_injected_faults_appear_as_span_events_with_matching_trace(tmp_path):
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    path = str(tmp_path / "chaos.jsonl")
    tracing.configure(path)
    faults.configure("ladder.rung:raise,nth=1,count=2")
    try:
        rng = np.random.default_rng(13)
        lines = [
            json.dumps({"id": f"r{i}",
                        "xy": (rng.random((8, 2)) * 50).tolist(),
                        "deadline_ms": 2500.0})
            for i in range(4)
        ]
        out = io.StringIO()
        run_jsonl(lines, out, ServiceConfig(threads=2, max_wait_ms=1.0))
    finally:
        faults.clear()
        tracing.configure(None)

    assert len(out.getvalue().strip().splitlines()) == 4
    spans = tracing.read_trace(path)
    assert tracing.orphan_spans(spans) == []  # retried/degraded trees close
    fault_events = [
        (s, ev)
        for s in spans
        for ev in s["events"]
        if ev["name"] == "fault_injected"
    ]
    assert fault_events, "no injected fault surfaced as a span event"
    roots = {
        s["trace_id"]: s for s in spans
        if s["name"] == "serve.request"
    }
    for span_rec, ev in fault_events:
        assert ev["attrs"]["seam"] == "ladder.rung"
        # the event's span belongs to a request trace — matching trace IDs
        assert span_rec["trace_id"] in roots
        assert span_rec["name"] == "ladder.rung"
    # the retry/degrade left its mark in the health delta too
    assert HEALTH.snapshot()["faults_injected"]["ladder.rung"] >= 1


@pytest.mark.chaos
def test_worker_seam_fault_event_reaches_the_trace(tmp_path):
    """The sched.flush seam fires on the WORKER thread (no active span):
    the injection event must still land in each waiting request's trace,
    attached to a flush span — including the tombstone flush emitted when
    the injection kills the worker."""
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    path = str(tmp_path / "flushchaos.jsonl")
    tracing.configure(path)
    faults.configure("sched.flush:raise,nth=1")
    try:
        rng = np.random.default_rng(17)
        lines = [
            json.dumps({"id": f"r{i}",
                        "xy": (rng.random((8, 2)) * 50).tolist(),
                        "deadline_ms": 4000.0})
            for i in range(3)
        ]
        out = io.StringIO()
        run_jsonl(lines, out, ServiceConfig(
            threads=3, max_wait_ms=1.0, watchdog_interval_s=0.05,
        ))
    finally:
        faults.clear()
        tracing.configure(None)

    assert len(out.getvalue().strip().splitlines()) == 3
    spans = tracing.read_trace(path)
    assert tracing.orphan_spans(spans) == []
    flush_fault_events = [
        ev
        for s in spans
        if s["name"] == "sched.flush"
        for ev in s["events"]
        if ev["name"] == "fault_injected"
    ]
    assert flush_fault_events, "worker-seam injection vanished from trace"
    assert all(
        ev["attrs"]["seam"] == "sched.flush" for ev in flush_fault_events
    )


@pytest.mark.serve
def test_queue_depth_gauge_drains_to_zero():
    svc, lines = _serve_session(5)
    assert len(lines) == 5
    svc.close()
    assert metrics.REGISTRY.value("serve_queue_depth_blocks") == 0


# -- obs report tool -----------------------------------------------------------


def test_obs_report_renders_trace_and_series(tmp_path, capsys):
    spec = importlib.util.spec_from_file_location(
        "obs_report", REPO / "tools" / "obs_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    trace_path = str(tmp_path / "t.jsonl")
    tracing.configure(trace_path)
    with tracing.span("request", id="r0"):
        with tracing.span("child"):
            tracing.add_event("fault_injected", seam="cache.get")
    tracing.configure(None)

    res = _tiny_solve()
    series_path = tmp_path / "solve.json"
    series_path.write_text(json.dumps(
        {"instance": "t9", "series": res.series}
    ) + "\n")

    rc = mod.main(["--trace", trace_path, "--series", str(series_path)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "request" in text and "child" in text
    assert "fault_injected" in text
    assert "0 orphans" in text
    assert "nodes_per_s" in text and "frontier" in text

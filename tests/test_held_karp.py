"""Held-Karp kernel vs golden per-block oracle solutions and brute force."""

import itertools
import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.generator import generate_instance
from tsp_mpi_reduction_tpu.ops.held_karp import (
    build_plan,
    solve_blocks,
    solve_blocks_from_dists,
)

CONFIGS = [
    "full_10x6_500x500.json",
    "full_5x10_1000x1000.json",
    "full_6x15_1000x1000.json",
    "full_5x50_1000x1000.json",
    "full_3x7_100x100.json",
    "full_4x9_1000x1000.json",
    "full_10x10_123x457.json",
    "full_13x4_1000x1000.json",
    "full_16x2_1000x1000.json",
]


def load(goldens_dir, name):
    g = json.loads((goldens_dir / name).read_text())
    cfg = g["config"]
    ids, xy = generate_instance(cfg["ncpb"], cfg["nblocks"], cfg["gx"], cfg["gy"])
    return g, ids, xy


@pytest.mark.parametrize("name", CONFIGS)
def test_block_costs_bit_exact(goldens_dir, name):
    g, ids, xy = load(goldens_dir, name)
    costs, tours = solve_blocks_from_dists(distance_matrix_np(xy))
    gold_costs = np.array([s["cost"] for s in g["block_solutions"]])
    np.testing.assert_array_equal(np.asarray(costs), gold_costs)


@pytest.mark.parametrize("name", CONFIGS)
def test_block_tours_exact(goldens_dir, name):
    g, ids, xy = load(goldens_dir, name)
    _, tours = solve_blocks_from_dists(distance_matrix_np(xy))
    # golden tours are global city-id sequences; ours are block-local indices
    got_ids = np.take_along_axis(
        ids, np.asarray(tours) % ids.shape[1], axis=1
    )  # tour entries are in [0, n], closing 0 maps to ids[:, 0]
    gold = np.array([s["ids"] for s in g["block_solutions"]])
    np.testing.assert_array_equal(got_ids, gold)


def test_brute_force_small():
    rng = np.random.default_rng(42)
    xy = rng.uniform(0, 100, size=(5, 7, 2))
    costs, tours = solve_blocks(xy)
    for b in range(5):
        d = np.sqrt(((xy[b, :, None] - xy[b, None, :]) ** 2).sum(-1))
        best = min(
            sum(d[p[i], p[i + 1]] for i in range(7))
            for perm in itertools.permutations(range(1, 7))
            for p in [(0,) + perm + (0,)]
        )
        assert abs(float(costs[b]) - best) < 1e-9
        # reported cost equals the measured length of the reported tour
        t = np.asarray(tours[b])
        measured = sum(d[t[i], t[i + 1]] for i in range(7))
        assert abs(float(costs[b]) - measured) < 1e-9


def test_tour_is_valid_permutation():
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 1000, size=(20, 12, 2))
    _, tours = solve_blocks(xy)
    t = np.asarray(tours)
    assert (t[:, 0] == 0).all() and (t[:, -1] == 0).all()
    assert (np.sort(t[:, :-1], axis=1) == np.arange(12)).all()


def test_float32_close_to_float64():
    rng = np.random.default_rng(1)
    xy = rng.uniform(0, 1000, size=(8, 10, 2))
    c64, _ = solve_blocks(xy, dtype="float64")
    c32, _ = solve_blocks(xy.astype(np.float32), dtype="float32")
    np.testing.assert_allclose(np.asarray(c32), np.asarray(c64), rtol=1e-5)


def test_plan_counts():
    p = build_plan(4)  # M=3: card1: 3 masks, card2: 3 masks
    assert p.scatter_idx.shape[0] == 2
    # states: 3*2 (c=1) + 3*1 (c=2) + 3 closing = 12
    assert p.dp_states == 12
    with pytest.raises(ValueError):
        build_plan(2)
    with pytest.raises(ValueError):
        build_plan(19)

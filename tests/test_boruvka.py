"""Boruvka MST bound kernel: exact equivalence with the Prim kernel.

The log-depth kernel (``_mst_conn_boruvka``) exists purely for the TPU's
latency profile; its certified VALUE must equal Prim's on every input —
all MSTs of a graph share one weight multiset, and the (weight, canonical
edge id) tie-break keeps each round cycle-free (see the kernel docstring).
Degrees may legitimately differ only when ties admit multiple MSTs.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.models.branch_bound import (
    _mst_conn,
    _mst_conn_boruvka,
)
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
from tsp_mpi_reduction_tpu.utils.tsplib import embedded


def _batch(n, k, seed, grid=None):
    """Random symmetric metric + lane masks; ``grid`` quantizes weights to
    integers, manufacturing heavy ties (the adversarial case for Boruvka's
    cycle-freedom)."""
    rng = np.random.default_rng(seed)
    d = rng.random((n, n))
    d = (d + d.T) / 2
    if grid:
        d = np.round(d * grid)
    np.fill_diagonal(d, 0)
    unvis = rng.random((k, n)) < 0.6
    cur = rng.integers(0, n, size=k)
    unvis[np.arange(k), cur] = False
    unvis[:, 0] = False  # city 0 is never mid-path-unvisited in the engine
    if k > 1:
        unvis[0, :] = False  # empty-U lane (padded/dead lane shape)
    if k > 2:
        unvis[1, :] = False
        unvis[1, min(3, n - 1)] = True  # singleton-U lane
    lam = rng.normal(0, 0.1, size=(k, n))
    return d, unvis, cur, lam


@pytest.mark.parametrize(
    "n,k,grid",
    [(17, 8, None), (51, 16, None), (23, 32, 64), (100, 8, 1000), (6, 4, 4)],
)
def test_value_matches_prim(n, k, grid):
    """f64 value equality on random metrics, with and without per-lane
    potentials, including tie-heavy integer grids (trailing lanes cover
    the empty-U and singleton-U degenerate shapes)."""
    d, unvis, cur, lam = _batch(n, k, seed=n * 1000 + k, grid=grid)
    dbar = jnp.asarray(d, jnp.float64)
    unvis_j = jnp.asarray(unvis)
    cur_j = jnp.asarray(cur, jnp.int32)
    for lamv in (None, jnp.asarray(lam, jnp.float64)):
        v1, g1 = _mst_conn(dbar, unvis_j, cur_j, n, lamv)
        v2, g2 = _mst_conn_boruvka(dbar, unvis_j, cur_j, n, lamv)
        v1, v2 = np.asarray(v1), np.asarray(v2)
        fin = np.isfinite(v1)
        assert (fin == np.isfinite(v2)).all()
        if fin.any():
            scale = max(1.0, float(grid or 1) * n)
            assert np.max(np.abs(v1[fin] - v2[fin])) < 1e-9 * scale
        # identical edge counts in any MST + identical connection bumps
        # => degree sums must agree even when the MSTs themselves differ
        assert (np.asarray(g1).sum(1) == np.asarray(g2).sum(1)).all()
        if grid is None:
            # generic position: the MST is unique, degrees must match too
            assert (np.asarray(g1) == np.asarray(g2)).all()


def test_integral_grid_f32_bitexact():
    """On the fixed-point integral path every weight is a grid multiple,
    so both kernels' f32 sums are exact — values must be bit-equal."""
    d, unvis, cur, _ = _batch(33, 16, seed=5, grid=100)
    dbar = jnp.asarray(d, jnp.float32)
    unvis_j = jnp.asarray(unvis)
    cur_j = jnp.asarray(cur, jnp.int32)
    v1, _ = _mst_conn(dbar, unvis_j, cur_j, 33)
    v2, _ = _mst_conn_boruvka(dbar, unvis_j, cur_j, 33)
    v1, v2 = np.asarray(v1), np.asarray(v2)
    fin = np.isfinite(v1)
    assert (v1[fin] == v2[fin]).all()


def _random_d(n, seed):
    xy = np.random.default_rng(seed).uniform(0, 100, (n, 2))
    return np.sqrt(((xy[:, None] - xy[None]) ** 2).sum(-1))


def test_solve_boruvka_matches_held_karp():
    """End-to-end proof with the Boruvka kernel equals the Held-Karp
    oracle, float and integral metrics."""
    for seed, integral in ((0, False), (2, True)):
        d = _random_d(12, seed)
        if integral:
            d = np.rint(d * 10)
        hk, _ = solve_blocks_from_dists(d[None])
        res = bb.solve(
            d, capacity=1 << 14, k=64, mst_kernel="boruvka"
        )
        assert res.proven_optimal
        assert abs(res.cost - float(hk[0])) < 1e-3


def test_solve_kernels_agree_node_for_node():
    """Same search trajectory on a tie-free instance: identical cost,
    proof, and node count (degrees match, so the ascent and therefore
    the pruning sequence are identical)."""
    d = _random_d(16, 7)
    r1 = bb.solve(d, capacity=1 << 12, k=32, mst_kernel="prim")
    r2 = bb.solve(d, capacity=1 << 12, k=32, mst_kernel="boruvka")
    assert r1.proven_optimal and r2.proven_optimal
    assert r1.cost == r2.cost
    assert r1.nodes_expanded == r2.nodes_expanded


def test_solve_boruvka_tsplib_root_closure():
    """ulysses16 (integral TSPLIB geo metric): the Boruvka-bounded engine
    must close at the root exactly like Prim's (root LB = optimum)."""
    inst = embedded("ulysses16")
    res = bb.solve(
        inst.distance_matrix(), capacity=1 << 14, k=64,
        mst_kernel="boruvka",
    )
    assert res.proven_optimal and res.cost == inst.known_optimum
    assert res.nodes_expanded == 1


def test_solve_sharded_boruvka():
    """The sharded engine accepts the kernel selector (8 virtual ranks)."""
    d = np.rint(_random_d(13, 3) * 10)
    hk, _ = solve_blocks_from_dists(d[None])
    res = bb.solve_sharded(
        d, make_rank_mesh(8), capacity_per_rank=1 << 11, k=16,
        mst_kernel="boruvka",
    )
    assert res.proven_optimal and res.cost == float(hk[0])

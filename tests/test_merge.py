"""Merge operator + fold vs golden fold costs and final tours."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.generator import generate_instance
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, fold_tours, make_padded, merge_tours

CONFIGS = [
    "full_10x6_500x500.json",
    "full_5x10_1000x1000.json",
    "full_6x15_1000x1000.json",
    "full_5x50_1000x1000.json",
    "full_3x7_100x100.json",
    "full_4x9_1000x1000.json",
    "full_10x10_123x457.json",
    "full_13x4_1000x1000.json",
    "full_16x2_1000x1000.json",
]


def setup(goldens_dir, name):
    g = json.loads((goldens_dir / name).read_text())
    cfg = g["config"]
    n, b = cfg["ncpb"], cfg["nblocks"]
    _, xy = generate_instance(n, b, cfg["gx"], cfg["gy"])
    dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
    costs, local_tours = solve_blocks_from_dists(distance_matrix_np(xy))
    global_tours = np.asarray(local_tours) + (np.arange(b)[:, None] * n)
    return g, n, b, dist, np.asarray(costs), global_tours


@pytest.mark.parametrize("name", CONFIGS)
def test_single_merge_matches_golden_first_fold(goldens_dir, name):
    g, n, b, dist, costs, tours = setup(goldens_dir, name)
    if b < 2:
        pytest.skip("needs >= 2 blocks")
    cap = 2 * n + 1
    t1 = make_padded(tours[0], n + 1, jnp.asarray(costs[0]), cap)
    t2 = make_padded(tours[1], n + 1, jnp.asarray(costs[1]), cap)
    merged = merge_tours(t1, t2, dist)
    assert float(merged.cost) == g["fold_costs"][0]
    assert int(merged.length) == 2 * n + 1


@pytest.mark.parametrize("name", CONFIGS)
def test_fold_final_bit_exact(goldens_dir, name):
    g, n, b, dist, costs, tours = setup(goldens_dir, name)
    ids, length, cost = fold_tours(jnp.asarray(tours), jnp.asarray(costs), dist)
    assert float(cost) == g["final"]["cost"]
    final_len = int(length)
    assert final_len == len(g["final"]["ids"])
    np.testing.assert_array_equal(np.asarray(ids)[:final_len], g["final"]["ids"])


def test_merge_rejects_oversized():
    with pytest.raises(ValueError):
        make_padded(np.arange(10), 10, 0.0, capacity=5)


def _host_tree_fold(tours, costs, dist):
    """Host mirror of fold_tours_tree's pairing, built from single
    merge_tours calls — the tree fold must match it bit-for-bit."""
    l = tours.shape[1]
    cur = [
        PaddedTour(jnp.asarray(t, jnp.int32), jnp.asarray(l, jnp.int32), c)
        for t, c in zip(tours, costs)
    ]
    while len(cur) > 1:
        out_cap = int(cur[0].ids.shape[0] + cur[1].ids.shape[0] - 1)
        nxt = []
        for i in range(0, len(cur) - 1, 2):
            t1 = make_padded(
                cur[i].ids, cur[i].length, cur[i].cost, out_cap
            )
            nxt.append(merge_tours(t1, cur[i + 1], dist))
        if len(cur) % 2:
            odd = cur[-1]
            nxt.append(make_padded(odd.ids, odd.length, odd.cost, out_cap))
        cur = nxt
    return cur[0]


@pytest.mark.parametrize("name", ["full_5x10_1000x1000.json", "full_5x50_1000x1000.json"])
def test_tree_fold_matches_host_tree_and_is_valid(goldens_dir, name):
    from tsp_mpi_reduction_tpu.ops.merge import fold_tours_tree

    g, n, b, dist, costs, tours = setup(goldens_dir, name)
    ids, length, cost = fold_tours_tree(jnp.asarray(tours), jnp.asarray(costs), dist)
    ref = _host_tree_fold(tours, costs, dist)
    assert int(length) == int(ref.length) == n * b + 1
    np.testing.assert_array_equal(
        np.asarray(ids)[: int(length)], np.asarray(ref.ids)[: int(length)]
    )
    assert float(cost) == float(ref.cost)
    # closed tour visiting every city exactly once
    t = np.asarray(ids)[: int(length)]
    assert t[0] == t[-1]
    assert sorted(t[:-1].tolist()) == list(range(n * b))


def test_tree_fold_xy_matches_gather_fold():
    """merge_tours_xy computes swap costs from coordinates with the same
    formula distance_matrix uses, so the xy tree fold must reproduce the
    gather tree fold exactly (same f32 values -> same argmin -> same
    splice) on the same float32 inputs."""
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix
    from tsp_mpi_reduction_tpu.ops.merge import fold_tours_tree, fold_tours_tree_xy

    rng = np.random.default_rng(3)
    n, b = 6, 7  # odd block count exercises the odd-tour carry path
    xy = jnp.asarray(rng.uniform(0, 100, (n * b, 2)), jnp.float32)
    dist = distance_matrix(xy)
    tours, costs = [], []
    for i in range(b):
        perm = rng.permutation(n) + i * n
        perm = np.roll(perm, -int(np.argmin(perm)))  # start at block min
        tours.append(np.concatenate([perm, perm[:1]]))
        d = np.asarray(dist)
        costs.append(np.float32(d[perm, np.roll(perm, -1)].sum()))
    tours = jnp.asarray(np.stack(tours), jnp.int32)
    costs = jnp.asarray(np.stack(costs))
    a_ids, a_len, a_cost = fold_tours_tree(tours, costs, dist)
    b_ids, b_len, b_cost = fold_tours_tree_xy(tours, costs, xy)
    assert int(a_len) == int(b_len)
    np.testing.assert_array_equal(np.asarray(a_ids), np.asarray(b_ids))
    assert float(a_cost) == float(b_cost)

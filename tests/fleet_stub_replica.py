"""Stub fleet replica: the serve JSONL contract without the jax import.

The fleet supervisor/front only need a process that speaks the line
protocol — spawning the real ``SolveService`` costs a jax import per
process, which would dominate the fast unit tests. This stub answers
every request with a host nearest-neighbor tour (pure stdlib, spawns in
~50 ms) and exposes failure knobs through its env:

- ``STUB_SLEEP_MS``       per-request sleep before answering
- ``STUB_DIE_AFTER``      exit(1) after answering N requests (a crash
                          mid-stream, for restart/re-dispatch tests)
- ``STUB_IGNORE_AFTER``   stop answering (but keep reading) after N
                          responses — a wedge without signals

Per-request ``_stub_sleep_ms`` overrides ``STUB_SLEEP_MS`` for that one
request (lets a test wedge exactly one request). Responses mirror the
serve schema fields the front relies on (id/n/cost/tour/tier/cache).
"""

import json
import math
import os
import sys
import time


def nn_tour(xy):
    n = len(xy)
    if n == 1:
        return 0.0, [0, 0]

    def d(a, b):
        dx, dy = xy[a][0] - xy[b][0], xy[a][1] - xy[b][1]
        return math.sqrt(dx * dx + dy * dy)

    visited = [False] * n
    visited[0] = True
    tour = [0]
    cost = 0.0
    cur = 0
    for _ in range(n - 1):
        best, best_d = -1, float("inf")
        for j in range(n):
            if not visited[j] and d(cur, j) < best_d:
                best, best_d = j, d(cur, j)
        cost += best_d
        tour.append(best)
        visited[best] = True
        cur = best
    cost += d(cur, 0)
    tour.append(0)
    return cost, tour


def main() -> int:
    sleep_ms = float(os.environ.get("STUB_SLEEP_MS", "0"))
    die_after = int(os.environ.get("STUB_DIE_AFTER", "0"))
    ignore_after = int(os.environ.get("STUB_IGNORE_AFTER", "0"))
    answered = 0
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        if ignore_after and answered >= ignore_after:
            continue  # the wedge: keep reading, never answer
        t0 = time.monotonic()
        pause = float(req.get("_stub_sleep_ms", sleep_ms))
        if pause:
            time.sleep(pause / 1000.0)
        try:
            cost, tour = nn_tour(req["xy"])
            resp = {
                "id": req.get("id"),
                "n": len(req["xy"]),
                "cost": cost,
                "tour": tour,
                "tier": "greedy",
                "certified_gap": None,
                "cache": "miss",
                "latency_ms": round((time.monotonic() - t0) * 1000.0, 3),
            }
        except (KeyError, TypeError, IndexError) as e:
            resp = {"id": req.get("id"), "error": str(e)}
        sys.stdout.write(json.dumps(resp) + "\n")
        sys.stdout.flush()
        answered += 1
        if die_after and answered >= die_after:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

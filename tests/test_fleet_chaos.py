"""Fleet chaos suite (ISSUE 11): real serve replicas, injected deaths.

Each test runs a REAL fleet — front in-process, ``SolveService`` replica
subprocesses via the serve CLI — while ``TSP_FAULTS`` kills or wedges a
replica mid-flight. The acceptance bar:

- every request answered EXACTLY ONCE with a valid closed tour
  (degraded tiers allowed — never a drop, never a duplicate);
- the self-healing actions (replica restart, re-dispatch, wedge kill)
  visible in the health counters and the front's stats fleet block;
- one stitched span tree per request across the front AND replica
  processes, with zero orphan spans — mid-flight kills included (the
  replica's announced root span keeps its children attached).

The ``front.dispatch`` seam is chaos-covered by
``test_fleet.py::test_dispatch_retry_capped_by_deadline`` (stub
replicas — the seam fires in the front, so the replica flavor is
irrelevant); :data:`FLEET_CHAOS_SEAMS` is what ``test_chaos.py``'s
completeness guard imports.
"""

import io
import json
import os
import time

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.fleet import FleetConfig, FleetFront
from tsp_mpi_reduction_tpu.fleet.supervisor import SupervisorConfig
from tsp_mpi_reduction_tpu.obs import tracing
from tsp_mpi_reduction_tpu.resilience import faults
from tsp_mpi_reduction_tpu.resilience.health import HEALTH
from tsp_mpi_reduction_tpu.serve.service import run_jsonl

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.fleet,
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    ),
]

#: the fleet seams this suite (plus the chaos-marked front.dispatch test
#: in test_fleet.py) exercises — imported by test_chaos.py's
#: every-seam-is-covered guard
FLEET_CHAOS_SEAMS = frozenset({"replica.kill", "replica.hang", "front.dispatch"})

_N = 6


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()
    tracing.configure(None)


def _cfg(tmp_path, replicas):
    return FleetConfig(
        replicas=replicas,
        threads=4,
        replica_threads=2,
        backend="cpu",
        shared_cache_dir=str(tmp_path / "shared"),
        compile_cache_dir=str(tmp_path / "cc"),
        default_deadline_ms=20_000.0,
        # generous hop: re-dispatch is driven by the supervisor's death
        # abort; a short hop would race the replicas' cold first compile
        hop_timeout_s=12.0,
        dispatch_attempts=4,
        supervisor=SupervisorConfig(
            probe_interval_s=0.1,
            wedge_timeout_s=1.5,
            startup_grace_s=3.0,
            scrape_timeout_s=0.4,
            restart_backoff_base_s=0.2,
            restart_backoff_max_s=1.0,
            healthy_reset_s=5.0,
        ),
    )


def _requests(count, seed, tight_every=0):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(count):
        deadline = 50.0 if (tight_every and i % tight_every == tight_every - 1) else 20_000.0
        reqs.append(
            {"id": f"q{i}", "xy": rng.uniform(0, 1000, (_N, 2)).tolist(),
             "deadline_ms": deadline}
        )
    return reqs


def _run(front, requests):
    out = io.StringIO()
    run_jsonl([json.dumps(r) + "\n" for r in requests], out, service=front)
    return [json.loads(ln) for ln in out.getvalue().strip().splitlines()]


def _assert_exactly_once_valid(responses, requests):
    ids = [r.get("id") for r in responses]
    assert len(responses) == len(requests), "dropped responses"
    assert len(set(ids)) == len(requests), "duplicate responses"
    for r in responses:
        assert "error" not in r, r
        tour = r["tour"]
        assert tour[0] == tour[-1] and sorted(tour[:-1]) == list(range(_N)), r


def _warm(front, count=2, seed=99):
    """Pay replica startup + first compiles outside the chaos window."""
    _run(front, [
        {"id": f"w{i}", "xy": np.random.default_rng(seed + i)
         .uniform(0, 1000, (_N, 2)).tolist(), "deadline_ms": 60_000.0}
        for i in range(count)
    ])


def test_fleet_replica_kill_mid_flight_exactly_once(tmp_path):
    """``replica.kill`` mid-flight: the in-flight request re-dispatches
    to the survivor, the corpse restarts on the backoff curve, and the
    stitched traces stay orphan-free."""
    trace = str(tmp_path / "trace.jsonl")
    tracing.configure(trace)
    front = FleetFront(_cfg(tmp_path, replicas=2))
    try:
        _warm(front)
        h0 = HEALTH.snapshot()
        faults.configure("replica.kill:raise,nth=3")
        requests = _requests(10, seed=1)
        responses = _run(front, requests)
        faults.clear()
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            if sum(r.restarts for r in front.supervisor.replicas) >= 1:
                break
            time.sleep(0.1)
        stats = json.loads(front.stats_json())
    finally:
        faults.clear()
        front.close()
        tracing.configure(None)
    _assert_exactly_once_valid(responses, requests)
    h = HEALTH.delta_since(h0)
    assert h["faults_injected"].get("replica.kill", 0) >= 1
    assert h["fleet_redispatches"] >= 1
    assert stats["fleet"]["restarts_total"] >= 1
    assert h["fleet_replica_restarts"] >= 1
    # trace reconstruction: one fleet.request tree per request, zero
    # orphans, and the replica processes' spans joined the front's trees
    spans = tracing.read_trace(trace)
    trees = tracing.build_trees(spans)
    roots = [
        root["span"]
        for t in trees.values()
        for root in t["roots"]
        if root["span"]["name"] == "fleet.request"
        and str(root["span"]["attrs"].get("id", "")).startswith("q")
    ]
    assert len(roots) == len(requests)
    assert tracing.orphan_spans(spans) == []
    assert any(sp["name"] == "serve.request" for sp in spans)  # stitched


def test_fleet_replica_hang_wedge_detected_exactly_once(tmp_path):
    """``replica.hang`` (SIGSTOP) mid-flight: the scrape probe stops
    answering, the wedge rule kills + restarts the replica, the hung
    request re-dispatches — and the resumed corpse's late answer (the
    SIGKILL beats SIGCONT here, but a slow teardown can still flush) is
    suppressed by first-writer-wins."""
    front = FleetFront(_cfg(tmp_path, replicas=2))
    try:
        _warm(front)
        h0 = HEALTH.snapshot()
        faults.configure("replica.hang:raise,nth=3")
        requests = _requests(10, seed=2, tight_every=5)
        responses = _run(front, requests)
        faults.clear()
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            if sum(r.restarts for r in front.supervisor.replicas) >= 1:
                break
            time.sleep(0.1)
        stats = json.loads(front.stats_json())
    finally:
        faults.clear()
        front.close()
    _assert_exactly_once_valid(responses, requests)
    h = HEALTH.delta_since(h0)
    assert h["faults_injected"].get("replica.hang", 0) >= 1
    assert h["stuck_restarts"] >= 1  # the wedge verdict fired
    assert h["fleet_redispatches"] >= 1
    assert stats["fleet"]["restarts_total"] >= 1


def test_fleet_cache_hits_cross_replica_boundary(tmp_path):
    """An instance solved by one replica is a cache HIT for a permuted,
    translated resubmission served by the OTHER replica — through the
    shared disk tier, with the answer's provenance saying so."""
    front = FleetFront(_cfg(tmp_path, replicas=2))
    rng = np.random.default_rng(11)
    xy = rng.uniform(0, 1000, (_N, 2))
    try:
        _warm(front)
        # solve on whichever replica; then resubmit enough permuted
        # copies that BOTH replicas see one (least-loaded spread)
        first = _run(front, [
            {"id": "orig", "xy": xy.tolist(), "deadline_ms": 60_000.0}
        ])
        resubs = [
            {"id": f"dup{i}",
             "xy": (xy[rng.permutation(_N)] + float(rng.integers(-300, 300))).tolist(),
             "deadline_ms": 60_000.0}
            for i in range(4)
        ]
        responses = _run(front, resubs)
        # the per-replica scrape totals refresh on the supervisor's
        # probe cadence — give it one beat before reading them
        time.sleep(1.0)
        stats = json.loads(front.stats_json())
    finally:
        front.close()
    assert "error" not in first[0]
    hits = [r for r in responses if r.get("cache") in ("hit", "refresh")]
    assert len(hits) >= 3  # resubmissions answered from cache
    for r in hits:
        assert abs(r["cost"] - first[0]["cost"]) < 1e-6
    # the disk tier carried at least one entry across a process boundary
    scrapes = [row["scrape"] for row in stats["fleet"]["replicas"]]
    assert sum(s.get("shared_cache_hits", 0) for s in scrapes) >= 1
    assert sum(s.get("shared_cache_publishes", 0) for s in scrapes) >= 1

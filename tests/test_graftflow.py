"""graftflow interprocedural dataflow lint (rules R9-R12).

Four surfaces:
- rule fixtures: each of R9-R12 fires on its hazard snippet and stays
  quiet on the clean rewrite (positive/negative per rule, including
  thread-reachability over nested closures, lock propagation into
  ``*_locked`` helpers, donation rebind patterns, bounded-loop statics,
  cross-module axis-name resolution);
- the meta-machinery shared with graftlint: inline disables, the ONE
  baseline file, per-rule ``--json`` counts, SARIF 2.1.0 output, the
  dead-scope ratchet for graftflow fingerprints, and the <= 10 s combined
  wall-time budget;
- the repo gate itself: the combined R1-R12 run must be clean;
- regressions for every real finding R9 surfaced (scheduler stats/close,
  ladder counts, PhaseTimer snapshot, Tracer path/active, FaultRegistry
  active), each asserting the access now happens UNDER the guarding lock,
  plus a threaded stress test hammering the exact pre-fix race shape.
"""

import json
import textwrap
import threading
import time

import pytest

from tsp_mpi_reduction_tpu.analysis import graftflow, graftlint
from tsp_mpi_reduction_tpu.analysis.__main__ import main as analysis_main
from tsp_mpi_reduction_tpu.analysis.graftflow import flow_project, flow_text

pytestmark = pytest.mark.lint  # rides the fast pre-push gate


def flow(src, **kw):
    return flow_text(textwrap.dedent(src), "fixture.py", **kw)


def rules_of(violations):
    return sorted({v.rule for v in violations})


# -- R9: lock-discipline races -------------------------------------------------

R9_RACY = """
    import threading

    class Sched:
        def __init__(self):
            self._lock = threading.Lock()
            self.flushes = 0
            self._thread = threading.Thread(target=self._worker)
            self._thread.start()

        def _worker(self):
            with self._lock:
                self.flushes += 1

        def stats(self):
            return {"flushes": self.flushes}
"""


def test_r9_fires_on_unlocked_read_in_threaded_class():
    vs = flow(R9_RACY)
    assert rules_of(vs) == ["R9"]
    assert vs[0].scope == "Sched.stats"


def test_r9_quiet_when_read_holds_the_lock():
    vs = flow(R9_RACY.replace(
        'return {"flushes": self.flushes}',
        'with self._lock:\n                return {"flushes": self.flushes}',
    ))
    assert vs == []


def test_r9_quiet_without_threads():
    # same lock discipline, but nothing ever runs concurrently
    vs = flow(R9_RACY.replace(
        "            self._thread = threading.Thread(target=self._worker)\n"
        "            self._thread.start()\n",
        "",
    ))
    assert vs == []


def test_r9_init_writes_are_exempt():
    # __init__ assigns guarded attrs before any thread can see the object
    vs = flow("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # pre-publication: no flag
                threading.Thread(target=self._tick).start()

            def _tick(self):
                with self._lock:
                    self.n += 1
    """)
    assert vs == []


def test_r9_lock_propagates_into_locked_helpers():
    # _bump is ONLY called with the lock held: its body is effectively
    # guarded (the call-site intersection), so no flag anywhere
    vs = flow("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self.n += 1

            def read(self):
                with self._lock:
                    return self.n
    """)
    assert vs == []


def test_r9_helper_called_with_and_without_lock_is_flagged():
    # one unlocked call site breaks the intersection: _bump's write races
    vs = flow("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self._bump()

            def poke(self):
                self._bump()

            def _bump(self):
                self.n += 1

            def set(self):
                with self._lock:
                    self.n = 5
    """)
    assert rules_of(vs) == ["R9"]
    assert any(v.scope == "S._bump" for v in vs)


def test_r9_dict_entry_mutation_counts_as_guarded_write():
    # self.counts[k] += 1 under the lock guards `counts`; the unlocked
    # dict() copy races the item store
    vs = flow("""
        import threading

        class Ladder:
            def __init__(self):
                self._lock = threading.Lock()
                self.counts = {}
                threading.Thread(target=self._answer).start()

            def _answer(self):
                with self._lock:
                    self.counts["bnb"] = self.counts.get("bnb", 0) + 1

            def stats(self):
                return dict(self.counts)
    """)
    assert rules_of(vs) == ["R9"]
    assert vs[0].scope == "Ladder.stats"


def test_r9_mutator_method_counts_as_guarded_write():
    vs = flow("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self._drain).start()

            def _drain(self):
                with self._lock:
                    self.items.append(1)

            def peek(self):
                return len(self.items)
    """)
    assert rules_of(vs) == ["R9"]


def test_r9_double_checked_locking_is_not_flagged():
    # unlocked pre-check re-validated under the lock in the same method
    vs = flow("""
        import threading

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.fh = None
                threading.Thread(target=self.emit).start()

            def configure(self, fh):
                with self._lock:
                    self.fh = fh

            def emit(self):
                if self.fh is None:
                    return
                with self._lock:
                    if self.fh is None:
                        return
                    self.fh.write("x")
    """)
    assert vs == []


def test_r9_double_check_through_callee_is_not_flagged():
    # the faults-registry shape: fire()'s lock-free fast path re-reads
    # the clause list under the lock inside _cross()
    vs = flow("""
        import threading

        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self.clauses = []
                threading.Thread(target=self.fire).start()

            def configure(self, cs):
                with self._lock:
                    self.clauses = cs

            def fire(self):
                if not self.clauses:
                    return
                self._cross()

            def _cross(self):
                with self._lock:
                    for c in self.clauses:
                        c()
    """)
    assert vs == []


def test_r9_cross_object_read_is_flagged():
    # the SolveService.stats_json shape: reaching into another class's
    # lock-guarded dict without its lock
    vs = flow("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Ladder:
            def __init__(self):
                self._lock = threading.Lock()
                self.tiers = {}

            def answer(self):
                with self._lock:
                    self.tiers["bnb"] = self.tiers.get("bnb", 0) + 1

        class Service:
            def __init__(self):
                self.ladder = Ladder()

            def handle(self, req):
                self.ladder.answer()

            def stats(self):
                return dict(self.ladder.tiers)

        def run(svc: Service, pool: ThreadPoolExecutor, reqs):
            for r in reqs:
                pool.submit(svc.handle, r)
    """)
    assert rules_of(vs) == ["R9"]
    assert vs[0].scope == "Service.stats"
    assert "Ladder" in vs[0].message


def test_r9_cross_object_locked_accessor_is_quiet():
    vs = flow("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        class Ladder:
            def __init__(self):
                self._lock = threading.Lock()
                self.tiers = {}

            def answer(self):
                with self._lock:
                    self.tiers["bnb"] = self.tiers.get("bnb", 0) + 1

            def snapshot(self):
                with self._lock:
                    return dict(self.tiers)

        class Service:
            def __init__(self):
                self.ladder = Ladder()

            def handle(self, req):
                self.ladder.answer()

            def stats(self):
                return self.ladder.snapshot()

        def run(svc: Service, pool: ThreadPoolExecutor, reqs):
            for r in reqs:
                pool.submit(svc.handle, r)
    """)
    assert vs == []


def test_r9_global_instance_through_import_alias():
    # the TRACER.path shape, across modules and an import alias
    vs = flow_project({
        "pkg/obs/tracing.py": textwrap.dedent("""
            import threading

            class Tracer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.path = None

                def configure(self, path):
                    with self._lock:
                        self.path = path

            TRACER = Tracer()
        """),
        "pkg/serve/service.py": textwrap.dedent("""
            import threading
            from ..obs import tracing as _tracing

            class Service:
                def __init__(self):
                    threading.Thread(target=self.handle).start()

                def handle(self):
                    _tracing.TRACER.configure("x")

                def stats(self):
                    return _tracing.TRACER.path
        """),
    })
    assert rules_of(vs) == ["R9"]
    assert "Tracer" in vs[0].message


def test_r9_property_access_is_exempt():
    vs = flow_project({
        "pkg/obs/tracing.py": textwrap.dedent("""
            import threading

            class Tracer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._path = None

                def configure(self, path):
                    with self._lock:
                        self._path = path

                @property
                def path(self):
                    with self._lock:
                        return self._path

            TRACER = Tracer()
        """),
        "pkg/serve/service.py": textwrap.dedent("""
            import threading
            from ..obs import tracing as _tracing

            class Service:
                def __init__(self):
                    threading.Thread(target=self.handle).start()

                def handle(self):
                    _tracing.TRACER.configure("x")

                def stats(self):
                    return _tracing.TRACER.path
        """),
    })
    assert vs == []


def test_r9_thread_reachability_through_nested_closures():
    # the thread target is a nested def whose call chain reaches the
    # class method that does the unlocked write
    vs = flow("""
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_bump(self):
                with self._lock:
                    self.n += 1

            def raw_bump(self):
                self.n += 1

        def serve(counter: Counter):
            def outer():
                def inner():
                    counter.raw_bump()
                    counter.locked_bump()
                inner()
            t = threading.Thread(target=outer)
            t.start()
    """)
    assert rules_of(vs) == ["R9"]
    assert vs[0].scope == "Counter.raw_bump"


def test_r9_disable_comment_on_line():
    src = R9_RACY.replace(
        'return {"flushes": self.flushes}',
        'return {"flushes": self.flushes}  # graftlint: disable=R9',
    )
    assert flow(src) == []


def test_r9_disable_comment_on_def_line():
    src = R9_RACY.replace(
        "def stats(self):",
        "def stats(self):  # graftlint: disable=R9",
    )
    assert flow(src) == []


def test_r9_plain_import_binds_the_root_package():
    # `import pkg.sub` binds `pkg` (Python semantics): `pkg.GLOBAL.attr`
    # must resolve against pkg/__init__, not pkg/sub
    vs = flow_project({
        "pkg/__init__.py": textwrap.dedent("""
            import threading

            class Reg:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def bump(self):
                    with self._lock:
                        self.count += 1

            GLOBAL = Reg()
        """),
        "pkg/sub.py": "x = 1\n",
        "app.py": textwrap.dedent("""
            import threading
            import pkg.sub

            def worker():
                pkg.GLOBAL.bump()
                return pkg.GLOBAL.count

            def run():
                threading.Thread(target=worker).start()
        """),
    })
    assert rules_of(vs) == ["R9"]
    assert "Reg" in vs[0].message and vs[0].scope == "worker"


def test_r9_non_executor_submit_is_not_a_thread_root():
    # a project class's own .submit() takes WORK ITEMS (the micro-batch
    # scheduler shape) — its argument must not become a phantom thread
    # entry point that marks the whole closure concurrent
    vs = flow("""
        import threading

        class Sched:
            def submit(self, item):
                return item

        class App:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.sched = Sched()

            def handler(self):
                with self._lock:
                    self.n += 1

            def kick(self):
                self.sched.submit(self.handler)

            def read(self):
                return self.n
    """)
    assert vs == []


# -- R10: use-after-donate -----------------------------------------------------

R10_DONATING = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnames=("fr",))
    def step(fr, x):
        return fr + x
"""


def test_r10_recognizes_fused_step_entry_and_rebind_discipline():
    """ISSUE 8 satellite: a fused-step-shaped donating entry (step_kernel
    static, Pallas push in the traced body) joins the R10 registry like
    any other — host code re-reading the donated frontier after the
    dispatch fires; the engine's rebind idiom stays quiet; the Pallas
    call INSIDE the jit-traced body is skipped (traced, not host code)."""
    fused_entry = """
    import jax
    from functools import partial
    from jax.experimental import pallas as pl

    @partial(jax.jit, static_argnames=("step_kernel",),
             donate_argnames=("fr",))
    def fused_step(fr, inc, step_kernel="fused"):
        nodes = pl.pallas_call(
            kern, out_shape=fr.nodes, input_output_aliases={0: 0}
        )(fr.nodes)
        return fr._replace(nodes=nodes), inc
    """
    vs = flow(fused_entry + """
    def host_bad(fr, inc):
        out, inc = fused_step(fr, inc)
        return fr.nodes
    """)
    assert rules_of(vs) == ["R10"]
    assert "fused_step" in vs[0].message
    vs = flow(fused_entry + """
    def host_good(fr, inc):
        fr, inc = fused_step(fr, inc)
        return fr.nodes
    """)
    assert vs == []


def test_r10_fires_on_use_after_donate():
    vs = flow(R10_DONATING + """
    def host(fr, x):
        out = step(fr, x)
        return fr.sum()
    """)
    assert rules_of(vs) == ["R10"]
    assert "step" in vs[0].message


def test_r10_same_statement_rebind_is_quiet():
    vs = flow(R10_DONATING + """
    def host(fr, x):
        fr = step(fr, x)
        return fr.sum()
    """)
    assert vs == []


def test_r10_use_between_donate_and_rebind_fires():
    vs = flow(R10_DONATING + """
    def host(fr, x):
        out = step(fr, x)
        stale = fr.shape
        fr = out
        return fr, stale
    """)
    assert rules_of(vs) == ["R10"]


def test_r10_branch_donation_fires_on_joined_use():
    vs = flow(R10_DONATING + """
    def host(fr, x, flag):
        if flag:
            out = step(fr, x)
        else:
            out = fr
        return fr.sum()
    """)
    assert rules_of(vs) == ["R10"]


def test_r10_rebind_on_both_branches_is_quiet():
    vs = flow(R10_DONATING + """
    def host(fr, x, flag):
        if flag:
            fr = step(fr, x)
        else:
            fr = step(fr, x * 2)
        return fr.sum()
    """)
    assert vs == []


def test_r10_loop_without_rebind_fires_via_back_edge():
    vs = flow(R10_DONATING + """
    def host(fr, xs):
        acc = 0
        for x in xs:
            out = step(fr, x)
            acc = acc + out
        return acc
    """)
    assert rules_of(vs) == ["R10"]


def test_r10_loop_with_rebind_is_quiet():
    vs = flow(R10_DONATING + """
    def host(fr, xs):
        for x in xs:
            fr = step(fr, x)
        return fr
    """)
    assert vs == []


def test_r10_check_donated_is_exempt():
    # the repo's sanctioned pattern: snapshot, dispatch, contract-check
    vs = flow(R10_DONATING + """
    from tsp_mpi_reduction_tpu.analysis import contracts as _contracts

    def host(fr, x):
        prev = fr
        fr = step(fr, x)
        _contracts.check_donated(prev, where="host")
        return fr
    """)
    assert vs == []


def test_r10_attribute_path_donation_is_field_precise():
    # donating fr.nodes kills fr.nodes (and deeper), NOT fr.overflow
    vs = flow("""
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def set_rows(nodes, rows):
        return nodes

    def writeback(fr, rows):
        out = set_rows(fr.nodes, rows)
        flag = fr.overflow
        shape = fr.nodes.shape
        return out, flag, shape
    """)
    assert [v.rule for v in vs] == ["R10"]
    assert "fr.nodes" in vs[0].message and "overflow" not in vs[0].message


def test_r10_keyword_donation():
    vs = flow(R10_DONATING + """
    def host(fr, x):
        out = step(x=x, fr=fr)
        return fr.sum()
    """)
    assert rules_of(vs) == ["R10"]


def test_r10_local_jit_entry_with_tuple_unwrap():
    # the sharded-solver shape: a function-local jax.jit(...) binding with
    # donate_argnums, dispatched as step(tuple(fr), ...)
    vs = flow("""
    import jax

    def solve_sharded(mesh, fr, ic, body):
        step = jax.jit(body, donate_argnums=(0,))
        while ic > 0:
            out = step(tuple(fr), ic)
            touched = fr.count
            fr = out[0]
            ic = out[1]
        return fr
    """)
    assert rules_of(vs) == ["R10"]
    assert "fr.count" in vs[0].message


def test_r10_wrapper_dispatch_tuple_pattern():
    # the AOT-dispatch shape: entry passed by name next to its arg tuple
    vs = flow(R10_DONATING + """
    def dispatch(entry, jit_fn, args, statics):
        return jit_fn(*args, **statics)

    def host(fr, x, k):
        out = dispatch("step", step, (fr, x), dict(k=k))
        stale = fr.shape
        fr = out
        return fr, stale
    """)
    assert rules_of(vs) == ["R10"]


def test_r10_traced_bodies_are_skipped():
    # inside another jit-traced function, inner donation is inlined by
    # XLA — host-level consumed-handle semantics don't apply
    vs = flow(R10_DONATING + """
    @jax.jit
    def outer(fr, x):
        out = step(fr, x)
        return out + fr
    """)
    assert vs == []


# -- R11: static-arg recompile risk --------------------------------------------

R11_ENTRY = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("ks",))
    def f(x, ks):
        return x
"""


def test_r11_list_static_fires():
    vs = flow(R11_ENTRY + """
    def call(x):
        return f(x, [1, 2])
    """)
    assert rules_of(vs) == ["R11"]
    assert "unhashable" in vs[0].message


def test_r11_tuple_static_is_quiet():
    vs = flow(R11_ENTRY + """
    def call(x):
        return f(x, (1, 2))
    """)
    assert vs == []


def test_r11_fstring_static_fires():
    vs = flow(R11_ENTRY + """
    def call(x, n):
        return f(x, f"bucket{n}")
    """)
    assert rules_of(vs) == ["R11"]
    assert "recompile" in vs[0].message


def test_r11_array_static_fires():
    vs = flow(R11_ENTRY + """
    import numpy as np

    def call(x):
        return f(x, np.array([1]))
    """)
    assert rules_of(vs) == ["R11"]


def test_r11_unbounded_loop_var_fires():
    vs = flow(R11_ENTRY + """
    def warm(x, sizes):
        for n in sizes:
            f(x, n)
    """)
    assert rules_of(vs) == ["R11"]
    assert "loop variable" in vs[0].message


def test_r11_bounded_literal_loop_is_the_precompile_pattern():
    vs = flow(R11_ENTRY + """
    def warm(x):
        for n in (8, 16, 32):
            f(x, n)
        for m in range(4):
            f(x, m)
    """)
    assert vs == []


def test_r11_local_bound_to_list_fires():
    vs = flow(R11_ENTRY + """
    def call(x):
        ks = [1, 2]
        return f(x, ks)
    """)
    assert rules_of(vs) == ["R11"]


def test_r11_static_argnums_positional():
    vs = flow("""
    import jax

    def g(x, k):
        return x

    gj = jax.jit(g, static_argnums=(1,))

    def call(x):
        return gj(x, {"a": 1})
    """)
    assert rules_of(vs) == ["R11"]


def test_r11_non_static_args_unaffected():
    vs = flow(R11_ENTRY + """
    def call(x):
        return f([1, 2, 3], ks=8)
    """)
    assert vs == []


def test_r11_keyword_static_binding():
    vs = flow(R11_ENTRY + """
    def call(x):
        return f(x, ks=[4, 5])
    """)
    assert rules_of(vs) == ["R11"]


def test_r11_balance_action_precompile_loop_is_quiet():
    """ISSUE 15's per-action entry warm-up: a bounded literal loop over
    the balance action names into a static arg is exactly the sanctioned
    precompile pattern — one compile per action, no churn."""
    vs = flow(R11_ENTRY + """
    def warm(x):
        for action in ("skip", "ring", "pair", "steal"):
            f(x, action)
    """)
    assert vs == []


def test_r11_ppermute_perm_table_as_static_fires():
    """A ppermute perm table is a list of pairs; binding one to a jit
    STATIC arg is the unhashable/recompile hazard the balance collectives
    avoid by closing over the table instead."""
    vs = flow(R11_ENTRY + """
    def call(x):
        perm = [(0, 1), (1, 0)]
        return f(x, perm)
    """)
    assert rules_of(vs) == ["R11"]


# -- R12: collective/axis-name consistency -------------------------------------


def test_r12_axis_typo_fires():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    RANK_AXIS = "ranks"

    def build(mesh):
        def body(x):
            return jax.lax.psum(x, "rank")
        return shard_map(body, mesh=mesh,
                         in_specs=(P(RANK_AXIS),), out_specs=P(RANK_AXIS))
    """)
    assert rules_of(vs) == ["R12"]
    assert "'rank'" in vs[0].message and "ranks" in vs[0].message


def test_r12_matching_axis_is_quiet():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    RANK_AXIS = "ranks"

    def build(mesh):
        def body(x):
            cnt = jax.lax.all_gather(x, RANK_AXIS)
            me = jax.lax.axis_index(RANK_AXIS)
            return jax.lax.ppermute(cnt, RANK_AXIS, [(0, 1)]) + me
        return shard_map(body, mesh=mesh,
                         in_specs=(P(RANK_AXIS),), out_specs=P(RANK_AXIS))
    """)
    assert vs == []


def test_r12_cross_module_constant_resolution():
    vs = flow_project({
        "pkg/parallel/mesh.py": 'RANK_AXIS = "ranks"\n',
        "pkg/parallel/reduce.py": textwrap.dedent("""
            import jax
            from jax.sharding import PartitionSpec as P
            from ..utils.backend import shard_map
            from .mesh import RANK_AXIS

            def build(mesh):
                def body(x):
                    return jax.lax.psum(x, RANK_AXIS)
                return shard_map(body, mesh=mesh,
                                 in_specs=(P(RANK_AXIS),),
                                 out_specs=P(RANK_AXIS))
        """),
    })
    assert vs == []


def test_r12_unresolvable_axis_is_skipped_not_guessed():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def build(mesh, axis):
        def body(x):
            return jax.lax.psum(x, axis)
        return shard_map(body, mesh=mesh,
                         in_specs=(P("ranks"),), out_specs=P("ranks"))
    """)
    assert vs == []


def test_r12_no_resolvable_specs_skips_the_site():
    vs = flow("""
    import jax
    from jax.experimental.shard_map import shard_map

    def build(mesh, specs):
        def body(x):
            return jax.lax.psum(x, "anything")
        return shard_map(body, mesh=mesh, in_specs=specs, out_specs=specs)
    """)
    assert vs == []


def test_r12_tuple_axis_names_are_each_checked():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def build(mesh):
        def body(x):
            return jax.lax.psum(x, ("x", "z"))
        return shard_map(body, mesh=mesh,
                         in_specs=(P("x", "y"),), out_specs=P("x"))
    """)
    assert [v.rule for v in vs] == ["R12"]
    assert "'z'" in vs[0].message


def test_r12_collective_inside_nested_lambda_is_checked():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def build(mesh):
        def body(acc):
            return jax.tree.map(
                lambda x: jax.lax.ppermute(x, "wrong", [(0, 1)]), acc
            )
        return shard_map(body, mesh=mesh,
                         in_specs=(P("ranks"),), out_specs=P("ranks"))
    """)
    assert rules_of(vs) == ["R12"]


R12_STEAL_BODY = """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    RANK_AXIS = "ranks"

    def build(mesh):
        def steal_body(nodes, cnt, round_i):
            all_c = jax.lax.all_gather(cnt, RANK_AXIS)
            me = jax.lax.axis_index(RANK_AXIS)
            slabs = jax.lax.all_gather(nodes, {slab_axis})
            donor = jnp.searchsorted(all_c, cnt, side="right") - 1
            return slabs[donor], all_c[me] + round_i
        return shard_map(steal_body, mesh=mesh,
                         in_specs=(P(RANK_AXIS), P(RANK_AXIS), P()),
                         out_specs=(P(RANK_AXIS), P(RANK_AXIS)))
"""


def test_r12_steal_collective_matching_axes_quiet():
    """ISSUE 15's steal collective shape — all-gathered counts feeding a
    searchsorted donor route plus a slab all_gather, every collective
    under RANK_AXIS — must lint clean as written."""
    vs = flow(R12_STEAL_BODY.format(slab_axis="RANK_AXIS"))
    assert vs == []


def test_r12_steal_collective_axis_drift_fires():
    """The same body with ONE collective's axis drifted (the slab gather
    on a stale name) is exactly the drift R12 exists to catch."""
    vs = flow(R12_STEAL_BODY.format(slab_axis='"rank"'))
    assert rules_of(vs) == ["R12"]
    assert "'rank'" in vs[0].message


def test_r12_scopes_are_baselineable(tmp_path):
    # findings in lambda and nested-def shard_map bodies must carry a
    # scope collect_scopes can re-derive, or an accepted baseline entry
    # would immediately read as DEAD debt and wedge the gate
    src = textwrap.dedent("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def build(mesh):
        def body(x):
            return jax.lax.psum(x, "wrong")
        lam = shard_map(lambda x: jax.lax.pmax(x, "also_wrong"),
                        mesh=mesh, in_specs=(P("ranks"),),
                        out_specs=P("ranks"))
        return shard_map(body, mesh=mesh,
                         in_specs=(P("ranks"),), out_specs=P("ranks")), lam
    """)
    fixture = tmp_path / "meshy.py"
    fixture.write_text(src)
    vs = flow_text(src, "meshy.py")
    assert sorted(v.scope for v in vs) == ["build", "build.body"]
    baseline_path = tmp_path / "baseline.json"
    graftlint.write_baseline(baseline_path, vs)
    baseline = graftlint.load_baseline(baseline_path)
    assert graftlint.apply_baseline(vs, baseline).new == []
    # none of the accepted scopes is dead (tmp_path acts as the root)
    assert graftlint.find_dead_scopes(baseline, tmp_path) == []


def test_cli_write_baseline_rejects_json_and_sarif(tmp_path, capsys):
    bad = tmp_path / "racy.py"
    bad.write_text(textwrap.dedent(R9_RACY))
    baseline = tmp_path / "baseline.json"
    rc = analysis_main([str(bad), "--write-baseline",
                        "--baseline", str(baseline), "--json"])
    assert rc == 2 and not baseline.exists()
    assert "cannot be combined" in capsys.readouterr().out
    rc = analysis_main([str(bad), "--write-baseline",
                        "--baseline", str(baseline),
                        "--sarif", str(tmp_path / "out.sarif")])
    assert rc == 2 and not (tmp_path / "out.sarif").exists()


def test_r12_disable_comment():
    vs = flow("""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def build(mesh):
        def body(x):
            return jax.lax.psum(x, "rank")  # graftlint: disable=R12
        return shard_map(body, mesh=mesh,
                         in_specs=(P("ranks"),), out_specs=P("ranks"))
    """)
    assert vs == []


# -- shared baseline / ratchet interplay ---------------------------------------


def test_flow_violations_share_graftlint_baseline_machinery(tmp_path):
    vs = flow(R9_RACY)
    path = tmp_path / "baseline.json"
    graftlint.write_baseline(path, vs)
    res = graftlint.apply_baseline(vs, graftlint.load_baseline(path))
    assert res.new == [] and len(res.accepted) == len(vs)
    # a second, different finding is NEW even with the baseline applied
    more = vs + flow(R10_DONATING + """
    def host(fr, x):
        out = step(fr, x)
        return fr.sum()
    """)
    res2 = graftlint.apply_baseline(more, graftlint.load_baseline(path))
    assert [v.rule for v in res2.new] == ["R10"]


def test_cli_json_reports_per_rule_counts(tmp_path, capsys):
    bad = tmp_path / "racy.py"
    bad.write_text(textwrap.dedent(R9_RACY))
    rc = analysis_main([str(bad), "--no-baseline", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["new"] == 1
    assert out["per_rule"]["R9"]["new"] == 1
    assert out["per_rule"]["R1"] == {"new": 0, "baselined": 0}
    assert out["violations"][0]["rule"] == "R9"


def test_cli_dead_baseline_scope_fails_for_flow_rules(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "entries": {"no_such_dir/gone.py::R9::Gone.meth::self.n += 1": 1},
    }))
    rc = analysis_main([str(clean), "--baseline", str(baseline)])
    assert rc == 1
    assert "DEAD baseline entry" in capsys.readouterr().out


def test_cli_baselined_flow_finding_passes(tmp_path, capsys):
    bad = tmp_path / "racy.py"
    bad.write_text(textwrap.dedent(R9_RACY))
    baseline = tmp_path / "baseline.json"
    assert analysis_main(
        [str(bad), "--write-baseline", "--baseline", str(baseline)]
    ) == 0
    capsys.readouterr()
    assert analysis_main([str(bad), "--baseline", str(baseline)]) == 0
    assert "1 baselined" in capsys.readouterr().out


# -- SARIF ---------------------------------------------------------------------

#: condensed SARIF 2.1.0 schema: the required-property and enum
#: constraints of the official OASIS schema for the subset we emit (the
#: full 500 kB schema is not vendored; jsonschema validates against this)
_SARIF_21_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                        },
                                    },
                                },
                            }
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["message"],
                            "properties": {
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "ruleId": {"type": "string"},
                                "level": {
                                    "enum": [
                                        "none", "note", "warning", "error",
                                    ]
                                },
                                "locations": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "properties": {
                                                            "uri": {
                                                                "type": "string"
                                                            }
                                                        },
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            }
                                                        },
                                                    },
                                                },
                                            }
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def test_sarif_output_validates_against_21_schema(tmp_path):
    bad = tmp_path / "racy.py"
    bad.write_text(textwrap.dedent(R9_RACY))
    sarif_path = tmp_path / "out.sarif"
    rc = analysis_main(
        [str(bad), "--no-baseline", "--quiet", "--sarif", str(sarif_path)]
    )
    assert rc == 1
    doc = json.loads(sarif_path.read_text())
    jsonschema = pytest.importorskip("jsonschema")
    jsonschema.validate(doc, _SARIF_21_SCHEMA)
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert ids == sorted(ids) and {"R1", "R9", "R12"} <= set(ids)
    (result,) = run["results"]
    assert result["ruleId"] == "R9" and result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("racy.py")
    assert loc["region"]["startLine"] > 1
    # the ratchet's line-free identity rides along for CI dedupe
    assert "::" in result["partialFingerprints"]["graftlint/v1"]


def test_sarif_clean_run_emits_empty_results(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    sarif_path = tmp_path / "out.sarif"
    assert analysis_main(
        [str(clean), "--no-baseline", "--quiet", "--sarif", str(sarif_path)]
    ) == 0
    doc = json.loads(sarif_path.read_text())
    assert doc["runs"][0]["results"] == []
    # rule catalog is stable even when clean (CI trend lines)
    assert len(doc["runs"][0]["tool"]["driver"]["rules"]) == 13


# -- the repo gate + latency budget --------------------------------------------


def test_repo_is_clean_and_combined_lint_fits_latency_budget(capsys):
    """The combined R1-R12 run over the real repo (exactly what
    ``make lint`` runs) is clean modulo the checked-in baseline AND
    finishes within the 10 s budget — the dataflow pass must not rot
    tier-1/pre-push latency."""
    t0 = time.perf_counter()
    rc = analysis_main([])
    wall = time.perf_counter() - t0
    out = capsys.readouterr().out
    assert rc == 0, out
    assert wall <= 10.0, f"combined lint took {wall:.2f}s (budget 10s)"


def test_lint_report_tool_renders_rule_table(tmp_path, capsys):
    import tools.lint_report as lr

    sarif_path = tmp_path / "report.sarif"
    rc = lr.main(["--sarif", str(sarif_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "R9" in out and "R12" in out and "verdict: ok" in out
    assert json.loads(sarif_path.read_text())["version"] == "2.1.0"


# -- regressions for the real findings R9 surfaced (drained in-code) -----------


class CountingCondition(threading.Condition):
    """Condition that counts context-manager acquisitions."""

    def __init__(self):
        super().__init__()
        self.entered = 0

    def __enter__(self):
        self.entered += 1
        return super().__enter__()


class CountingLock:
    """Lock wrapper counting acquisitions (for plain Lock attributes)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.entered = 0

    def __enter__(self):
        self.entered += 1
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        self.entered += 1
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()


def test_fix_scheduler_stats_snapshots_under_cv():
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    sched = MicroBatchScheduler()
    sched._cv = CountingCondition()
    before = sched._cv.entered
    stats = sched.stats()
    assert sched._cv.entered > before  # pre-fix: unlocked counter reads
    assert stats["batches"] == 0


def test_fix_scheduler_close_snapshots_thread_handles_under_cv():
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    sched = MicroBatchScheduler()
    sched._cv = CountingCondition()
    before = sched._cv.entered
    sched.close()
    # pre-fix close read/reset self._thread/_watchdog outside the lock
    assert sched._cv.entered >= before + 2


def test_fix_ladder_counts_snapshot_is_locked_and_copies():
    from tsp_mpi_reduction_tpu.serve.ladder import DeadlineLadder
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    with MicroBatchScheduler() as sched:
        ladder = DeadlineLadder(sched)
        ladder._count_lock = CountingLock()
        tiers, failures = ladder.counts_snapshot()
        assert ladder._count_lock.entered == 1
        # snapshots are COPIES: mutating them can't corrupt the ladder
        tiers["bnb"] += 100
        assert ladder.counts_snapshot()[0]["bnb"] == 0
        assert set(failures) == {"bnb", "pipeline", "greedy"}


def test_fix_phase_timer_snapshot_is_locked():
    from tsp_mpi_reduction_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    timer.add("solve", 0.25)
    timer._lock = CountingLock()
    snap = timer.snapshot()
    assert timer._lock.entered == 1
    assert snap == {"solve": 0.25}
    snap["solve"] = 99.0  # a copy, not the live table
    assert timer.snapshot()["solve"] == 0.25


def test_fix_tracer_path_and_active_read_under_lock(tmp_path):
    from tsp_mpi_reduction_tpu.obs.tracing import Tracer

    tr = Tracer()
    tr.configure(str(tmp_path / "t.jsonl"))
    tr._lock = CountingLock()
    before = tr._lock.entered
    assert tr.path == str(tmp_path / "t.jsonl")
    assert tr._lock.entered > before
    before = tr._lock.entered
    assert tr.active in (True, False)
    assert tr._lock.entered > before
    tr.configure(None)
    assert tr.path is None


def test_fix_fault_registry_active_reads_under_lock():
    from tsp_mpi_reduction_tpu.resilience.faults import FaultRegistry

    reg = FaultRegistry("ckpt.write:raise")
    reg._lock = CountingLock()
    assert reg.active is True
    assert reg._lock.entered == 1


def test_r9_stress_ladder_counts_survive_racing_reporting():
    """Threaded stress on the exact pre-fix race shape: request threads
    hammer the ladder's guarded count dicts while a reader loops the
    locked snapshot. Deterministic acceptance: every one of the 200
    increments lands (no lost updates, no torn dict reads) and every
    observed snapshot is internally consistent."""
    from tsp_mpi_reduction_tpu.serve.ladder import DeadlineLadder
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    with MicroBatchScheduler() as sched:
        ladder = DeadlineLadder(sched)

        def boom():
            raise RuntimeError("injected rung failure")

        stop = threading.Event()
        torn = []

        def reader():
            while not stop.is_set():
                tiers, failures = ladder.counts_snapshot()
                if any(v < 0 for v in failures.values()):
                    torn.append(failures)

        def writer():
            for _ in range(25):
                assert ladder._attempt("bnb", 8, boom) is None

        rt = threading.Thread(target=reader)
        rt.start()
        writers = [threading.Thread(target=writer) for _ in range(8)]
        for w in writers:
            w.start()
        for w in writers:
            w.join()
        stop.set()
        rt.join()
        assert torn == []
        assert ladder.counts_snapshot()[1]["bnb"] == 8 * 25


def test_r9_stress_phase_timer_snapshot_during_key_growth():
    """Pre-fix, reporting copied ``timer.seconds`` while other threads
    inserted NEW phase keys — dict iteration during resize raises
    RuntimeError. The locked snapshot must survive unbounded key growth
    with every recorded phase present and exact."""
    from tsp_mpi_reduction_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            try:
                timer.snapshot()
            except RuntimeError as e:  # pragma: no cover — the pre-fix bug
                errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for i in range(600):
        timer.add(f"phase{i}", 0.001)
    stop.set()
    for t in threads:
        t.join()
    assert errors == []
    snap = timer.snapshot()
    assert len(snap) == 600
    assert abs(sum(snap.values()) - 0.6) < 1e-9

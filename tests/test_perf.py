"""Compile-once execution layer (perf/): cache keys, AOT store, donation.

Four surfaces:
- cache-key invalidation: any change to jax version string, backend,
  dtype, shape, or a static arg must change the key — a stale executable
  can never be loaded for a config it was not compiled for;
- the AOT serialized-executable store: miss -> validated write -> hit,
  corrupt-file degradation, disabled-cache no-op;
- buffer donation: ``_expand_loop`` output aliases its input frontier on
  CPU (pointer identity), the donating spill writebacks alias, and the
  ``check_donated`` contract distinguishes consumed from live buffers;
- the host-setup memo + canonicalization fast path + scheduler warmup.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.analysis import contracts
from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.perf import compile_cache as cc
from tsp_mpi_reduction_tpu.perf import donation
from tsp_mpi_reduction_tpu.utils import tsplib


@pytest.fixture
def perf_dir(tmp_path, monkeypatch):
    """Enable the perf store into a throwaway dir (no jax.config edits —
    only the AOT/memo layers, which is what these tests exercise)."""
    monkeypatch.setattr(cc, "_enabled_dir", str(tmp_path))
    return tmp_path


@pytest.fixture
def perf_off(monkeypatch):
    monkeypatch.setattr(cc, "_enabled_dir", None)


def _d(name="burma14"):
    return tsplib.resolve_instance(name).distance_matrix()


# -- cache keys ----------------------------------------------------------------


def _key(**over):
    base = dict(
        name="entry",
        args=(jax.ShapeDtypeStruct((4, 4), jnp.float32),),
        statics={"k": 8, "n": 4},
        backend="cpu",
        jax_version="0.4.37+0.4.36",
    )
    base.update(over)
    return cc.entry_key(
        base["name"], base["args"], base["statics"],
        backend=base["backend"], jax_version=base["jax_version"],
    )


def test_key_stable_for_identical_config():
    assert _key() == _key()


@pytest.mark.parametrize(
    "change",
    [
        {"jax_version": "0.4.38+0.4.37"},
        {"backend": "tpu"},
        {"args": (jax.ShapeDtypeStruct((4, 4), jnp.float64),)},  # dtype
        {"args": (jax.ShapeDtypeStruct((8, 4), jnp.float32),)},  # shape
        {"statics": {"k": 16, "n": 4}},  # static arg value
        {"statics": {"k": 8, "n": 4, "push_block": 0}},  # static arg set
        {"name": "entry2"},
    ],
    ids=["jax-version", "backend", "dtype", "shape", "static-value",
         "static-set", "entry-name"],
)
def test_key_invalidates_on_any_config_change(change):
    assert _key(**change) != _key()


def test_key_covers_pytree_leaves():
    fr_a = bb.Frontier(
        jax.ShapeDtypeStruct((64, 23), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.bool_),
    )
    fr_b = fr_a._replace(nodes=jax.ShapeDtypeStruct((128, 23), jnp.int32))
    assert _key(args=(fr_a,)) != _key(args=(fr_b,))


# -- AOT serialized-executable store ------------------------------------------


def _toy_jit():
    from functools import partial

    @partial(jax.jit, static_argnames=("k",))
    def f(x, k):
        return x * k + 1.0

    return f


def test_aot_store_miss_then_hit(perf_dir):
    f = _toy_jit()
    x = jnp.ones((8,), jnp.float32)
    before = cc.STATS.snapshot()
    c1 = cc.aot_load_or_compile("toy", f, (x,), {"k": 3})
    c2 = cc.aot_load_or_compile("toy", f, (x,), {"k": 3})
    after = cc.STATS.snapshot()
    assert c1 is not None and c2 is not None
    assert after["aot_misses"] == before["aot_misses"] + 1
    assert after["aot_hits"] == before["aot_hits"] + 1
    # both executables compute the same thing as the jit path
    np.testing.assert_allclose(np.asarray(c2(x)), np.asarray(f(x, k=3)))
    # a hit records the recorded compile cost as savings
    assert after["compile_seconds_saved"] > before["compile_seconds_saved"]


def test_aot_store_different_static_misses(perf_dir):
    f = _toy_jit()
    x = jnp.ones((8,), jnp.float32)
    cc.aot_load_or_compile("toy2", f, (x,), {"k": 3})
    before = cc.STATS.snapshot()
    c = cc.aot_load_or_compile("toy2", f, (x,), {"k": 5})  # static changed
    after = cc.STATS.snapshot()
    assert after["aot_misses"] == before["aot_misses"] + 1
    np.testing.assert_allclose(np.asarray(c(x)), np.asarray(f(x, k=5)))


def test_aot_store_corrupt_file_degrades_to_compile(perf_dir):
    f = _toy_jit()
    x = jnp.ones((4,), jnp.float32)
    cc.aot_load_or_compile("toy3", f, (x,), {"k": 2})
    key = cc.entry_key("toy3", (x,), {"k": 2})
    exec_path, _meta, _uns = cc._aot_paths(key)
    with open(exec_path, "wb") as fh:
        fh.write(b"garbage")
    # the corrupt-store scenario is a WARM PROCESS reading a torn disk
    # entry — drop the in-process executable memo so the load path
    # actually re-reads the file (the memo otherwise never touches disk)
    cc._AOT_LOADED.clear()
    before = cc.STATS.snapshot()
    c = cc.aot_load_or_compile("toy3", f, (x,), {"k": 2})
    after = cc.STATS.snapshot()
    assert c is not None  # degraded to a fresh compile, not a crash
    assert after["aot_errors"] == before["aot_errors"] + 1
    np.testing.assert_allclose(np.asarray(c(x)), np.asarray(f(x, k=2)))


def test_aot_store_disabled_returns_none(perf_off):
    f = _toy_jit()
    assert cc.aot_load_or_compile("toy4", f, (jnp.ones(3),), {"k": 2}) is None


# -- host-setup memo -----------------------------------------------------------


def test_ascent_memo_roundtrip_bit_identical(perf_dir):
    d = _d("burma14")
    pi = np.random.default_rng(0).random(d.shape[0])
    assert cc.ascent_memo_get(d, "one-tree", 400) is None  # cold
    cc.ascent_memo_put(d, "one-tree", 400, pi)
    got = cc.ascent_memo_get(d, "one-tree", 400)
    np.testing.assert_array_equal(got, pi)  # byte-exact
    # a different instance / step count misses
    assert cc.ascent_memo_get(d + 1.0, "one-tree", 400) is None
    assert cc.ascent_memo_get(d, "one-tree", 200) is None


def test_ascent_memo_solve_results_identical(perf_dir):
    d = _d("burma14")
    cold = bb.solve(d, capacity=2048, k=32, ils_rounds=0)  # populates memo
    warm = bb.solve(d, capacity=2048, k=32, ils_rounds=0)  # memo hit
    assert cc.STATS.snapshot()["ascent_memo_hits"] >= 1
    assert cold.cost == warm.cost
    assert cold.root_lower_bound == warm.root_lower_bound


def test_ascent_memo_memory_tier_works_without_disk(perf_off):
    """The in-process LRU tier (ISSUE 13) answers even with no cache dir
    enabled — it is what caps the serve scheduler's per-resume overhead,
    and a resumed slice must not pay the root ascent again just because
    TSP_COMPILE_CACHE is unset."""
    d = _d("burma14")
    pi = np.random.default_rng(1).random(d.shape[0])
    assert cc.ascent_memo_get(d, "one-tree", 400) is None
    cc.ascent_memo_put(d, "one-tree", 400, pi)
    got = cc.ascent_memo_get(d, "one-tree", 400)
    np.testing.assert_array_equal(got, pi)
    # returned arrays are COPIES: a caller scribbling on one must not
    # poison the memo for the next resume
    got[:] = -1.0
    np.testing.assert_array_equal(cc.ascent_memo_get(d, "one-tree", 400), pi)


def test_ascent_memo_memory_lru_evicts_oldest(perf_off):
    base = _d("burma14")
    pi = np.random.default_rng(2).random(base.shape[0])
    for i in range(cc._ASCENT_MEM_CAP + 1):
        cc.ascent_memo_put(base + float(i), "one-tree", 400, pi)
    # the first entry rolled off; the newest survives
    assert cc.ascent_memo_get(base, "one-tree", 400) is None
    got = cc.ascent_memo_get(
        base + float(cc._ASCENT_MEM_CAP), "one-tree", 400
    )
    np.testing.assert_array_equal(got, pi)
    cc.ascent_memo_reset_memory()
    assert cc.ascent_memo_get(
        base + float(cc._ASCENT_MEM_CAP), "one-tree", 400
    ) is None


# -- buffer donation -----------------------------------------------------------


def _warm_frontier(n=10, capacity=512, k=16):
    d = _d("burma14")[:n, :n]
    bd = bb._bound_setup(d, "one-tree", node_ascent=0)
    d64 = np.asarray(d, np.float64)
    tour = bb.nearest_neighbor_tour(d64)
    fr = bb.make_root_frontier(
        n, capacity, np.asarray(bd.min_out, np.float64), pad_rows=k * n
    )
    args = (
        jnp.asarray(bb.tour_cost(d64, tour), jnp.float32),
        jnp.asarray(tour, jnp.int32),
        jnp.asarray(d, jnp.float32),
        bd.min_out, bd.bound_adj, bd.dbar, bd.pi, bd.slack,
        bd.ascent_step, bd.lam_budget,
    )
    return fr, args, bd, n, k


def test_expand_loop_output_aliases_donated_input():
    """The ISSUE 5 donation contract: on CPU the dispatch writes the new
    frontier into the SAME allocation (pointer identity), and the old
    handle is consumed."""
    fr, args, bd, n, k = _warm_frontier()
    p_in = fr.nodes.unsafe_buffer_pointer()
    out = bb._expand_loop(
        fr, *args, k, n, 4, bool(bd.integral), True, 0
    )
    assert out[0].nodes.unsafe_buffer_pointer() == p_in
    assert fr.nodes.is_deleted()
    # the consumed handle must raise on re-read, not return stale bytes
    with pytest.raises(RuntimeError):
        np.asarray(fr.nodes)


def test_expand_loop_ref_twin_does_not_donate():
    fr, args, bd, n, k = _warm_frontier()
    out = bb._expand_loop_ref(
        fr, *args, k, n, 2, bool(bd.integral), True, 0
    )
    assert not fr.nodes.is_deleted()  # re-dispatchable harness twin
    out2 = bb._expand_loop_ref(
        fr, *args, k, n, 2, bool(bd.integral), True, 0
    )
    np.testing.assert_array_equal(
        np.asarray(out[0].nodes), np.asarray(out2[0].nodes)
    )


def test_donating_row_write_aliases():
    nodes = jnp.zeros((256, 21), jnp.int32)
    rows = jnp.ones((7, 21), jnp.int32)
    p_in = nodes.unsafe_buffer_pointer()
    out = donation.set_rows_donated(nodes, rows)
    assert out.unsafe_buffer_pointer() == p_in
    got = np.asarray(out)
    assert (got[:7] == 1).all() and (got[7:] == 0).all()


def test_donating_rank_row_write_aliases():
    nodes = jnp.zeros((4, 64, 21), jnp.int32)
    block = jnp.ones((2, 5, 21), jnp.int32)
    p_in = nodes.unsafe_buffer_pointer()
    out = donation.set_rank_rows_donated(
        nodes, jnp.asarray([1, 3], jnp.int32), block
    )
    assert out.unsafe_buffer_pointer() == p_in
    got = np.asarray(out)
    assert (got[1, :5] == 1).all() and (got[0] == 0).all()
    assert (got[3, :5] == 1).all() and (got[1, 5:] == 0).all()


def test_check_donated_contract():
    consumed = jnp.ones((8,))
    jax.jit(lambda x: x + 1, donate_argnums=0)(consumed)
    contracts.check_donated(consumed, where="test")  # consumed: passes
    live = jnp.ones((8,))
    with pytest.raises(contracts.ContractError, match="donation did not"):
        contracts.check_donated(live, where="test")


def test_check_donated_off_level(monkeypatch):
    monkeypatch.setenv("TSP_CONTRACTS", "off")
    contracts.check_donated(jnp.ones(3), where="test")  # no-op


def test_solve_results_unchanged_by_aot_dispatch(perf_dir):
    """solve() through the AOT store (cache enabled) must equal the plain
    jit path bit-for-bit — same optimum, same proof, same node count."""
    d = _d("burma14")
    warm = bb.solve(d, capacity=2048, k=32, ils_rounds=0)  # populates
    again = bb.solve(d, capacity=2048, k=32, ils_rounds=0)  # AOT hits
    cc._enabled_dir = None
    try:
        plain = bb.solve(d, capacity=2048, k=32, ils_rounds=0)
    finally:
        cc._enabled_dir = str(perf_dir)
    assert warm.cost == again.cost == plain.cost
    assert warm.proven_optimal and again.proven_optimal and plain.proven_optimal
    assert warm.nodes_expanded == again.nodes_expanded == plain.nodes_expanded


# -- serve warmup + host-path trim ---------------------------------------------


def test_scheduler_precompile_counts_and_equivalence():
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    rng = np.random.default_rng(3)
    xy = rng.random((4, 6, 2)) * 100.0
    diff = xy[:, :, None, :] - xy[:, None, :, :]
    dists = np.sqrt(np.sum(diff * diff, axis=-1))
    with MicroBatchScheduler(max_batch=4, max_wait_ms=1.0) as cold_s:
        cold = cold_s.submit(dists).wait(timeout=120.0)
    with MicroBatchScheduler(max_batch=4, max_wait_ms=1.0) as warm_s:
        warmed = warm_s.precompile([6])
        assert warmed >= 1
        assert warm_s.stats()["precompiled_buckets"] == warmed
        assert warm_s.stats()["precompile_seconds"] >= 0.0
        warm = warm_s.submit(dists).wait(timeout=120.0)
    np.testing.assert_array_equal(np.asarray(cold[1]), np.asarray(warm[1]))
    np.testing.assert_allclose(np.asarray(cold[0]), np.asarray(warm[0]))


def test_scheduler_precompile_skips_invalid_sizes():
    from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

    with MicroBatchScheduler(max_batch=2) as s:
        assert s.precompile([1, 2, 99]) == 0  # out of [3, MAX_BLOCK_CITIES]


def test_canonical_cache_skips_sort_on_identical_and_translated():
    from tsp_mpi_reduction_tpu.serve import canonical as canon

    cache = canon.CanonicalCache(16)
    rng = np.random.default_rng(0)
    # grid-aligned base: jitter invariance is guaranteed only strictly
    # below step/2 AROUND A GRID POINT (canonical.py module docstring)
    xy = np.round(rng.random((12, 2)) * 1000.0, 3)
    a = canon.canonicalize_cached(xy, cache)
    assert cache.stats()["sorts_saved"] == 0
    b = canon.canonicalize_cached(xy.copy(), cache)  # identical resubmit
    c = canon.canonicalize_cached(xy + 77.0, cache)  # translated
    jit = xy + (rng.random((12, 2)) - 0.5) * 1e-4  # sub-half-step jitter
    e = canon.canonicalize_cached(jit, cache)
    assert cache.stats()["sorts_saved"] == 3
    assert a.key == b.key == c.key == e.key
    np.testing.assert_array_equal(a.perm, b.perm)


def test_canonical_cache_permuted_resubmit_same_key_slow_path():
    from tsp_mpi_reduction_tpu.serve import canonical as canon

    cache = canon.CanonicalCache(16)
    rng = np.random.default_rng(1)
    xy = rng.random((10, 2)) * 1000.0
    a = canon.canonicalize_cached(xy, cache)
    perm = rng.permutation(10)
    b = canon.canonicalize_cached(xy[perm], cache)  # reordered cities
    assert a.key == b.key  # same canonical instance...
    assert cache.stats()["sorts_saved"] == 0  # ...but the sort was needed
    assert cache.stats()["raw_misses"] == 2


def test_canonicalize_cached_none_cache_is_canonicalize():
    from tsp_mpi_reduction_tpu.serve import canonical as canon

    xy = np.random.default_rng(2).random((8, 2)) * 10.0
    assert (
        canon.canonicalize_cached(xy, None).key == canon.canonicalize(xy).key
    )
    with pytest.raises(ValueError):
        canon.canonicalize_cached(np.ones((3, 3)), canon.CanonicalCache())


def test_service_stats_carry_compile_and_canonical_counters():
    import io

    from tsp_mpi_reduction_tpu.serve.service import (
        ServiceConfig,
        run_jsonl,
    )

    rng = np.random.default_rng(5)
    xy = np.round(rng.random((6, 2)) * 100.0, 3)  # grid-aligned (see above)
    reqs = [json.dumps({"id": f"r{i}", "xy": (xy + i).tolist()}) for i in range(4)]
    out = io.StringIO()
    # threads=1: the sorts-saved count below assumes r0 primes the
    # canonical memo BEFORE r1 canonicalizes — with 2 request threads
    # r0/r1 can race the priming and both pay the sort (observed as a
    # rare saved==2 flake); this test is about the stats plumbing, not
    # request concurrency
    svc = run_jsonl(reqs, out, ServiceConfig(threads=1, max_batch=4))
    lines = [json.loads(line) for line in out.getvalue().splitlines()]
    assert [ln["id"] for ln in lines] == ["r0", "r1", "r2", "r3"]
    stats = json.loads(svc.stats_json())
    assert stats["cache"]["canonical_sorts_saved"] == 3  # r1-r3 fast-path
    assert "compile_cache" in stats
    assert "aot_hits" in stats["compile_cache"]


def test_writer_batches_burst_in_order():
    """A burst of already-resolved responses drains as one write, in
    input order, with nothing lost (the batched-writer trim)."""
    import io

    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    rng = np.random.default_rng(6)
    reqs = [
        json.dumps({"id": f"b{i}", "xy": (rng.random((5, 2)) * 50).tolist()})
        for i in range(24)
    ]
    out = io.StringIO()
    run_jsonl(reqs, out, ServiceConfig(threads=8, max_batch=8))
    got = [json.loads(line)["id"] for line in out.getvalue().splitlines()]
    assert got == [f"b{i}" for i in range(24)]

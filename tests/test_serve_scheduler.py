"""Micro-batch scheduler (serve.scheduler): grouping, padding, correctness.

Small n keeps Held-Karp compiles cheap; the scheduler's bucket set is
restricted per-test so the suite compiles a handful of shapes, not eight.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

pytestmark = pytest.mark.serve

N = 6  # block size for every scheduler test: one compile per bucket shape


def _instances(rng, count, n=N):
    return np.stack([distance_matrix_np(rng.uniform(0, 100, (n, 2))) for _ in range(count)])


def test_batched_results_match_direct_solve():
    rng = np.random.default_rng(0)
    ds = _instances(rng, 8)
    ref_costs, ref_tours = solve_blocks_from_dists(
        jnp.asarray(ds, jnp.float32), jnp.float32
    )
    with MicroBatchScheduler(max_batch=8, max_wait_ms=20.0, buckets=(8,)) as s:
        tickets = [s.submit(ds[i : i + 1]) for i in range(8)]
        results = [t.wait(timeout=60.0) for t in tickets]
    assert all(r is not None for r in results)
    for i, (costs, tours) in enumerate(results):
        assert costs.shape == (1,) and tours.shape == (1, N + 1)
        np.testing.assert_array_equal(tours[0], np.asarray(ref_tours)[i])
        np.testing.assert_allclose(costs[0], np.asarray(ref_costs)[i], rtol=1e-6)


def test_concurrent_submissions_form_batches():
    rng = np.random.default_rng(1)
    ds = _instances(rng, 16)
    with MicroBatchScheduler(max_batch=16, max_wait_ms=50.0, buckets=(16,)) as s:
        barrier = threading.Barrier(16)
        results = [None] * 16

        def submit(i):
            barrier.wait()
            results[i] = s.submit(ds[i : i + 1]).wait(timeout=60.0)

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = s.stats()
    assert all(r is not None for r in results)
    assert stats["blocks_solved"] == 16
    # 16 concurrent submissions must NOT take 16 device calls
    assert stats["batches"] < 16, f"no batching happened: {stats}"
    assert stats["queue_depth_hwm"] > 1


def test_multi_block_submission_and_padding_occupancy():
    rng = np.random.default_rng(2)
    ds = _instances(rng, 5)
    with MicroBatchScheduler(max_batch=8, max_wait_ms=1.0, buckets=(8,)) as s:
        costs, tours = s.submit(ds).wait(timeout=60.0)
        stats = s.stats()
    assert costs.shape == (5,) and tours.shape == (5, N + 1)
    assert stats["blocks_solved"] == 5
    assert stats["padded_blocks"] == 8  # padded up to the bucket
    assert 0 < stats["batch_occupancy"] < 1


def test_mixed_shapes_grouped_separately():
    rng = np.random.default_rng(3)
    d6 = _instances(rng, 2, n=6)
    d7 = _instances(rng, 2, n=7)
    with MicroBatchScheduler(max_batch=4, max_wait_ms=5.0, buckets=(2, 4)) as s:
        t6 = [s.submit(d6[i : i + 1]) for i in range(2)]
        t7 = [s.submit(d7[i : i + 1]) for i in range(2)]
        r6 = [t.wait(timeout=60.0) for t in t6]
        r7 = [t.wait(timeout=60.0) for t in t7]
    assert all(r is not None for r in r6 + r7)
    assert r6[0][1].shape == (1, 7) and r7[0][1].shape == (1, 8)


def test_submit_validation_is_synchronous():
    with MicroBatchScheduler() as s:
        with pytest.raises(ValueError):
            s.submit(np.zeros((1, 2, 2)))  # n < 3
        with pytest.raises(ValueError):
            s.submit(np.zeros((1, 19, 19)))  # n > MAX_BLOCK_CITIES
        with pytest.raises(ValueError):
            s.submit(np.zeros((4, 4)))  # not [B, n, n]
        with pytest.raises(ValueError):
            s.submit(np.zeros((0, 6, 6)))  # empty


def test_close_fails_pending_and_rejects_new():
    s = MicroBatchScheduler(max_wait_ms=10_000.0)  # worker will sit waiting
    s.close()
    with pytest.raises(RuntimeError):
        s.submit(np.zeros((1, 6, 6)))
    s.close()  # idempotent


def test_oversized_submission_flushes_alone():
    rng = np.random.default_rng(4)
    ds = _instances(rng, 3)
    # max_batch=2 < submission's 3 blocks: must still flush, not starve
    with MicroBatchScheduler(max_batch=2, max_wait_ms=1.0, buckets=(2, 4)) as s:
        got = s.submit(ds).wait(timeout=60.0)
    assert got is not None and got[0].shape == (3,)

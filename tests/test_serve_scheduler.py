"""Micro-batch scheduler (serve.scheduler): grouping, padding, correctness.

Small n keeps Held-Karp compiles cheap; the scheduler's bucket set is
restricted per-test so the suite compiles a handful of shapes, not eight.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

pytestmark = pytest.mark.serve

N = 6  # block size for every scheduler test: one compile per bucket shape


def _instances(rng, count, n=N):
    return np.stack([distance_matrix_np(rng.uniform(0, 100, (n, 2))) for _ in range(count)])


def test_batched_results_match_direct_solve():
    rng = np.random.default_rng(0)
    ds = _instances(rng, 8)
    ref_costs, ref_tours = solve_blocks_from_dists(
        jnp.asarray(ds, jnp.float32), jnp.float32
    )
    with MicroBatchScheduler(max_batch=8, max_wait_ms=20.0, buckets=(8,)) as s:
        tickets = [s.submit(ds[i : i + 1]) for i in range(8)]
        results = [t.wait(timeout=60.0) for t in tickets]
    assert all(r is not None for r in results)
    for i, (costs, tours) in enumerate(results):
        assert costs.shape == (1,) and tours.shape == (1, N + 1)
        np.testing.assert_array_equal(tours[0], np.asarray(ref_tours)[i])
        np.testing.assert_allclose(costs[0], np.asarray(ref_costs)[i], rtol=1e-6)


def test_concurrent_submissions_form_batches():
    rng = np.random.default_rng(1)
    ds = _instances(rng, 16)
    with MicroBatchScheduler(max_batch=16, max_wait_ms=50.0, buckets=(16,)) as s:
        barrier = threading.Barrier(16)
        results = [None] * 16

        def submit(i):
            barrier.wait()
            results[i] = s.submit(ds[i : i + 1]).wait(timeout=60.0)

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = s.stats()
    assert all(r is not None for r in results)
    assert stats["blocks_solved"] == 16
    # 16 concurrent submissions must NOT take 16 device calls
    assert stats["batches"] < 16, f"no batching happened: {stats}"
    assert stats["queue_depth_hwm"] > 1


def test_multi_block_submission_and_padding_occupancy():
    rng = np.random.default_rng(2)
    ds = _instances(rng, 5)
    with MicroBatchScheduler(max_batch=8, max_wait_ms=1.0, buckets=(8,)) as s:
        costs, tours = s.submit(ds).wait(timeout=60.0)
        stats = s.stats()
    assert costs.shape == (5,) and tours.shape == (5, N + 1)
    assert stats["blocks_solved"] == 5
    assert stats["padded_blocks"] == 8  # padded up to the bucket
    assert 0 < stats["batch_occupancy"] < 1


def test_mixed_shapes_grouped_separately():
    rng = np.random.default_rng(3)
    d6 = _instances(rng, 2, n=6)
    d7 = _instances(rng, 2, n=7)
    with MicroBatchScheduler(max_batch=4, max_wait_ms=5.0, buckets=(2, 4)) as s:
        t6 = [s.submit(d6[i : i + 1]) for i in range(2)]
        t7 = [s.submit(d7[i : i + 1]) for i in range(2)]
        r6 = [t.wait(timeout=60.0) for t in t6]
        r7 = [t.wait(timeout=60.0) for t in t7]
    assert all(r is not None for r in r6 + r7)
    assert r6[0][1].shape == (1, 7) and r7[0][1].shape == (1, 8)


def test_submit_validation_is_synchronous():
    with MicroBatchScheduler() as s:
        with pytest.raises(ValueError):
            s.submit(np.zeros((1, 2, 2)))  # n < 3
        with pytest.raises(ValueError):
            s.submit(np.zeros((1, 19, 19)))  # n > MAX_BLOCK_CITIES
        with pytest.raises(ValueError):
            s.submit(np.zeros((4, 4)))  # not [B, n, n]
        with pytest.raises(ValueError):
            s.submit(np.zeros((0, 6, 6)))  # empty


def test_close_fails_pending_and_rejects_new():
    s = MicroBatchScheduler(max_wait_ms=10_000.0)  # worker will sit waiting
    s.close()
    with pytest.raises(RuntimeError):
        s.submit(np.zeros((1, 6, 6)))
    s.close()  # idempotent


def test_oversized_submission_flushes_alone():
    rng = np.random.default_rng(4)
    ds = _instances(rng, 3)
    # max_batch=2 < submission's 3 blocks: must still flush, not starve
    with MicroBatchScheduler(max_batch=2, max_wait_ms=1.0, buckets=(2, 4)) as s:
        got = s.submit(ds).wait(timeout=60.0)
    assert got is not None and got[0].shape == (3,)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_watchdog_revives_killed_worker_and_requeues_inflight():
    """A worker killed mid-flush (the sched.flush fault seam) must not
    strand its tickets: the watchdog re-queues the in-flight group and a
    fresh worker generation answers it."""
    from tsp_mpi_reduction_tpu.resilience import faults
    from tsp_mpi_reduction_tpu.resilience.health import HEALTH

    faults.configure("sched.flush:raise")
    try:
        before = HEALTH.get("worker_restarts")
        rng = np.random.default_rng(5)
        ds = _instances(rng, 2)
        with MicroBatchScheduler(
            max_batch=2, max_wait_ms=5.0, buckets=(2,),
            watchdog_interval_s=0.05,
        ) as s:
            tickets = [s.submit(ds[i : i + 1]) for i in range(2)]
            results = [t.wait(timeout=60.0) for t in tickets]
            stats = s.stats()
        assert all(r is not None for r in results)
        ref_costs, _ = solve_blocks_from_dists(
            jnp.asarray(ds, jnp.float32), jnp.float32
        )
        for i, (costs, _tours) in enumerate(results):
            np.testing.assert_allclose(
                costs[0], np.asarray(ref_costs)[i], rtol=1e-6
            )
        assert stats["worker_restarts"] >= 1
        assert HEALTH.get("worker_restarts") > before
    finally:
        faults.clear()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_submission_after_worker_death_revives_without_watchdog_tick():
    """submit() itself checks the worker's pulse — a dead worker found
    between watchdog ticks is revived synchronously."""
    from tsp_mpi_reduction_tpu.resilience import faults

    faults.configure("sched.flush:raise")
    try:
        rng = np.random.default_rng(6)
        ds = _instances(rng, 2)
        # watchdog effectively disabled: only submit() can revive
        with MicroBatchScheduler(
            max_batch=1, max_wait_ms=1.0, buckets=(1,),
            watchdog_interval_s=3600.0,
        ) as s:
            t1 = s.submit(ds[0:1])
            time.sleep(0.3)  # the worker pops t1, hits the seam, and dies
            t2 = s.submit(ds[1:2])  # revives the worker AND requeues t1
            r2 = t2.wait(timeout=60.0)
            r1 = t1.wait(timeout=60.0)
        assert r2 is not None and r1 is not None
    finally:
        faults.clear()


def test_ticket_outcome_is_first_writer_wins():
    """After a watchdog revive two generations can touch one ticket: the
    first outcome sticks — a stale worker's late failure must not mask a
    valid replacement result, nor a late duplicate result a real error."""
    from tsp_mpi_reduction_tpu.serve.scheduler import Ticket

    t = Ticket(np.zeros((1, 4, 4)))
    t._resolve(np.asarray([1.5]), np.asarray([[0, 1, 2, 3, 0]]))
    t._fail(RuntimeError("stale generation's late failure"))
    costs, tours = t.wait(timeout=1.0)  # must NOT raise
    assert float(costs[0]) == 1.5

    t2 = Ticket(np.zeros((1, 4, 4)))
    t2._fail(RuntimeError("real failure"))
    t2._resolve(np.asarray([9.9]), np.asarray([[0, 1, 2, 3, 0]]))
    with pytest.raises(RuntimeError, match="real failure"):
        t2.wait(timeout=1.0)


def test_spill_fetch_retries_real_transfer_errors(monkeypatch):
    """The spill readback retry must absorb what flaky hardware actually
    raises (XlaRuntimeError, OSError), not only injected test faults."""
    from jaxlib.xla_extension import XlaRuntimeError

    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    assert XlaRuntimeError in bb._TRANSFER_ERRORS
    assert OSError in bb._TRANSFER_ERRORS

    calls = []

    class _FlakyOnce:
        def fire(self, seam):
            calls.append(seam)
            if len(calls) == 1:
                raise OSError("transient transfer failure")

    monkeypatch.setattr(bb, "_fault_registry", lambda: _FlakyOnce())
    out = bb._fetch_live_rows(jnp.arange(12, dtype=jnp.int32).reshape(3, 4), 2)
    assert out.shape == (2, 4) and len(calls) == 2  # retried, then fetched


def test_rung_retry_uses_remaining_budget_not_stale_capture():
    """A retry after a late transient fault must run with the time
    actually left, not the originally-captured budget — otherwise one
    fault nearly doubles the request's wall time past its deadline."""
    from tsp_mpi_reduction_tpu.resilience.faults import TransientFault
    from tsp_mpi_reduction_tpu.serve.ladder import DeadlineLadder, LadderConfig

    budgets = []

    def solver(d, time_limit_s):
        budgets.append(time_limit_s)
        if len(budgets) == 1:
            time.sleep(0.15)
            raise TransientFault("fault surfacing late in the rung")
        return 1.0, np.asarray([0, 1, 2, 3, 0], np.int32), 1.0, True

    cfg = LadderConfig(
        bnb_solver=solver, bnb_min_budget_s=0.0,
        prior_s={"bnb": 0.0, "pipeline": 0.0, "greedy": 0.0},
        retry_base_delay_s=0.001,
    )
    with MicroBatchScheduler() as sched:
        ladder = DeadlineLadder(sched, cfg)
        xy = np.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]])
        t0 = time.monotonic()
        res = ladder.solve(xy, deadline_s=0.25)
        elapsed = time.monotonic() - t0
    assert res.tier == "bnb" and len(budgets) == 2
    assert budgets[1] < budgets[0] * 0.75  # shrank to the real remainder
    assert elapsed < 0.5  # nowhere near 2x the deadline


def test_stuck_allowance_backs_off_but_stays_capped():
    """Successive stuck-revives double the watchdog's patience (cold
    compiles) but cap at 8x — a persistently wedging backend must not
    grow the allowance until stuck detection is effectively disabled."""
    s = MicroBatchScheduler(stuck_timeout_s=1.0)
    try:
        with s._cv:
            for _ in range(10):
                s._revive_locked(stuck=True)
            assert s._stuck_allowance == 8.0
            assert s.stuck_restarts == 10
    finally:
        s.close()

"""Chaos suite: one injected fault per run, at EVERY registered seam.

The acceptance bar for the resilience subsystem (ISSUE 4): with
``TSP_FAULTS`` arming exactly one seam per run (deterministic seed),

- the chunked B&B campaign still terminates with a correct incumbent and
  a monotone certified lower bound — a chunk "process" killed by a fault
  is simply restarted by the supervisor loop, exactly like a preempted
  subprocess, and ``restore`` resumes from the newest VALID snapshot;
- the serve loop answers 100% of a 32-request JSONL workload with VALID
  tours (degraded tiers allowed), with worker restarts and absorbed
  retries visible in the health counters.

A completeness guard asserts the union of exercised seams IS
``resilience.faults.SEAMS`` — a seam added without a chaos test fails
here, not in production.
"""

import io
import json
import os

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.resilience import faults
from tsp_mpi_reduction_tpu.resilience.faults import FaultInjected
from tsp_mpi_reduction_tpu.resilience.health import HEALTH
from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

pytestmark = [
    pytest.mark.chaos,
    # a worker thread dying with FaultInjected IS the scenario under test
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    ),
]


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def random_d(n, seed):
    rng = np.random.default_rng(seed)
    return distance_matrix_np(rng.uniform(0, 100, (n, 2)))


# -- chunked B&B campaign under solver-side faults -----------------------------

#: (spec, seam) — the solver-side seams, one per campaign run. nth=2 for
#: the write faults so the campaign HAS a previous snapshot to fall back
#: to (the nth=1 case — no valid snapshot ever existed — is the fresh
#: -start path, covered by test_resilience's raise-mode test).
SOLVER_SPECS = [
    ("ckpt.write:truncate,nth=2,seed=5", "ckpt.write"),
    ("ckpt.write:corrupt,nth=2,seed=5", "ckpt.write"),
    ("ckpt.read:raise", "ckpt.read"),
    ("spill.fetch:raise", "spill.fetch"),
]


@pytest.mark.parametrize("spec,seam", SOLVER_SPECS, ids=[s for s, _ in SOLVER_SPECS])
def test_chunked_campaign_survives_solver_fault(spec, seam, tmp_path):
    """Kill/corrupt at a solver seam mid-campaign; the supervisor loop
    (chunk crashed -> start next chunk) must still reach the PROVEN exact
    optimum with a certified LB that never regresses chunk-over-chunk."""
    d = np.rint(random_d(12, 33) * 10)
    hk_cost = float(solve_blocks_from_dists(d[None])[0][0])
    ckpt = str(tmp_path / "campaign.npz")
    # capacity/inner_steps sized so nodes flow through the host reservoir
    # (the spill.fetch seam must actually be crossed); this config proves
    # in ~1.9k expansion steps -> ~5 chunks of 400. One save per chunk
    # (no periodic cadence), so nth=2 faults exactly chunk 2's snapshot
    # and it is still the NEWEST when chunk 3 resumes — the fallback
    # restore is observed, not rotated away by a later clean save.
    kw = dict(capacity=256, k=8, inner_steps=1, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False)
    faults.configure(spec)
    health_before = HEALTH.snapshot()
    floors = []
    crashes = 0
    res = None
    for _chunk in range(15):
        resume = ckpt if os.path.exists(ckpt) else None
        try:
            res = bb.solve(d, max_iters=400, checkpoint_path=ckpt,
                           resume_from=resume, **kw)
        except FaultInjected:
            crashes += 1  # the chunk "process" died; supervisor moves on
            continue
        floors.append(res.lower_bound)
        if res.proven_optimal:
            break
    assert res is not None and res.proven_optimal
    assert res.cost == hk_cost  # correct incumbent despite the chaos
    # certified LB monotone across every surviving chunk, incl. the
    # resume that recovered from a torn/corrupt/unreadable snapshot
    assert floors == sorted(floors)
    assert faults.registry().hits(seam) > 0, f"seam {seam} never crossed"
    health = HEALTH.snapshot()
    if "truncate" in spec:
        # the torn publish killed a chunk AND the next resume skipped it
        assert crashes >= 1
        assert health["fallback_restores"] > health_before["fallback_restores"]
    elif "corrupt" in spec:
        # silent bit rot: no crash, but the checksum caught it on resume
        assert crashes == 0
        assert health["fallback_restores"] > health_before["fallback_restores"]
    elif seam in ("ckpt.read", "spill.fetch"):
        # transient raise: absorbed by the bounded retry, nothing lost
        assert health["retries"] > health_before["retries"]


def test_corrupt_checkpoint_resume_falls_back_and_stays_monotone(tmp_path):
    """The satellite's recovery shape, end to end at the bb API: snapshot
    A then B; B rots on disk; restore yields A (counted as a fallback
    restore) and the resumed chunk's certified LB still clamps to A's
    floor — monotone across the recovered resume."""
    d = np.rint(random_d(12, 33) * 10)
    ckpt = str(tmp_path / "rot.npz")
    kw = dict(capacity=1 << 13, k=8, inner_steps=1, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False)
    first = bb.solve(d, max_iters=3, checkpoint_path=ckpt, **kw)
    assert not first.proven_optimal
    second = bb.solve(d, max_iters=3, resume_from=ckpt, checkpoint_path=ckpt, **kw)
    with open(ckpt, "r+b") as f:  # bit-rot snapshot B's tail
        f.seek(-8, os.SEEK_END)
        f.write(b"\x00" * 8)
    before = HEALTH.get("fallback_restores")
    *_, lb_restored = bb.restore(ckpt, expect_d=d, expect_bound="min-out")
    assert HEALTH.get("fallback_restores") == before + 1
    assert lb_restored == pytest.approx(first.lower_bound)  # snapshot A's floor
    resumed = bb.solve(d, max_iters=3, resume_from=ckpt, checkpoint_path=ckpt, **kw)
    assert resumed.lower_bound >= first.lower_bound  # monotone across recovery
    assert resumed.lower_bound <= resumed.cost
    assert second.lower_bound >= first.lower_bound


# -- adaptive balance under a steal-escalation fault ---------------------------

#: (spec, seam) — the balance controller's escalation seam, armed for the
#: WHOLE campaign (count=0): every steal the controller attempts is
#: injected and must degrade that round to the base collective.
BALANCE_SPECS = [("balance.steal:raise,count=0", "balance.steal")]


@pytest.mark.parametrize(
    "spec,seam", BALANCE_SPECS, ids=[s for s, _ in BALANCE_SPECS]
)
def test_balance_steal_fault_degrades_and_stays_exact(spec, seam, tmp_path):
    """A balance.steal fault mid-solve (ISSUE 15 satellite): the sharded
    campaign runs chunked with adversarial single-rank seeding — the
    regime that escalates to steal constantly — with the seam armed the
    whole time. Every escalation degrades to the base diffusion action;
    the search must still prove the EXACT optimum with a certified LB
    monotone across chunks, and both the injections (health registry) and
    the degradations (obs.balance) must be visible."""
    from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh

    d = np.rint(random_d(12, 33) * 10)
    hk_cost = float(solve_blocks_from_dists(d[None])[0][0])
    mesh = make_rank_mesh(4)
    ckpt = str(tmp_path / "balance.npz")
    kw = dict(capacity_per_rank=256, k=8, inner_steps=1, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False,
              seed_mode="single-rank", balance="adaptive")
    faults.configure(spec)
    floors = []
    degraded = 0
    res = None
    for _chunk in range(15):
        resume = ckpt if os.path.exists(ckpt) else None
        res = bb.solve_sharded(d, mesh, max_iters=300, checkpoint_path=ckpt,
                               resume_from=resume, **kw)
        floors.append(res.lower_bound)
        degraded += res.balance["steal_degraded"]
        if res.proven_optimal:
            break
    assert res is not None and res.proven_optimal
    assert res.cost == hk_cost  # exact despite every steal being injected
    assert floors == sorted(floors)  # certified LB monotone across chunks
    assert faults.registry().hits(seam) > 0, "steal never escalated"
    assert degraded > 0  # the absorb path actually ran
    # the fault blocked EVERY steal: none may appear in the action mix
    assert res.balance["actions"].get("steal", 0) == 0
    assert res.balance["collective_dispatches"] > 0  # base action stood in
    health = HEALTH.snapshot()
    assert health["faults_injected"].get(seam, 0) >= degraded


# -- serve loop under service-side faults --------------------------------------

#: (spec, health counter that must move) — one service seam per workload.
SERVE_SPECS = [
    ("sched.flush:raise", "worker_restarts"),
    ("sched.flush:delay,delay_ms=700", "stuck_restarts"),
    ("ladder.rung:raise", "retries"),
    ("cache.get:raise", "retries"),
    ("cache.put:raise", "retries"),
]

_N = 8  # request size: pipeline rung -> single-block HK via the scheduler


def _workload(count=32, seed=11):
    """Deadlines sized ABOVE the pipeline rung's cold prior (0.5 s) so the
    ladder actually routes through the micro-batch scheduler — a budget
    under the prior would answer everything greedily and cross no seam."""
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(count):
        xy = rng.uniform(0, 100, (_N, 2))
        lines.append(json.dumps(
            {"id": f"r{i}", "xy": xy.tolist(), "deadline_ms": 2500.0}
        ) + "\n")
    return lines


@pytest.mark.parametrize("spec,counter", SERVE_SPECS, ids=[s for s, _ in SERVE_SPECS])
def test_serve_loop_answers_everything_under_fault(spec, counter):
    """32-request JSONL workload with one armed seam: every request gets
    a VALID closed tour (degraded tiers allowed), and the self-healing
    action (restart or retry) is visible in the health counters."""
    faults.configure(spec)
    before = HEALTH.snapshot()
    out = io.StringIO()
    cfg = ServiceConfig(
        threads=4,
        max_wait_ms=1.0,
        default_deadline_ms=2500.0,
        # fast supervision so the chaos run heals within a test timeout:
        # the knobs production would tune (README "Fault tolerance")
        watchdog_interval_s=0.05,
        stuck_timeout_s=0.2,
    )
    svc = run_jsonl(_workload(), out, cfg)
    lines = out.getvalue().strip().splitlines()
    assert len(lines) == 32  # 100% answered
    for line in lines:
        resp = json.loads(line)
        assert "error" not in resp, resp
        tour = resp["tour"]
        assert tour[0] == tour[-1] and sorted(tour[:-1]) == list(range(_N))
        assert resp["tier"] in ("bnb", "pipeline", "greedy")
        assert resp["cost"] > 0.0
    after = HEALTH.snapshot()
    assert after[counter] > before[counter], (
        f"{counter} did not move under {spec}"
    )
    # the self-healing evidence is scraper-visible in the stats line too
    stats = json.loads(svc.stats_json())
    assert stats["health"][counter] == after[counter]
    seam = spec.split(":", 1)[0]
    assert stats["health"]["faults_injected"].get(seam, 0) >= 1


def test_serve_loop_clean_run_has_quiet_health():
    """No faults armed: the same workload heals nothing — restarts stay
    zero-delta (the watchdog must not flap on a healthy worker)."""
    before = HEALTH.snapshot()
    out = io.StringIO()
    run_jsonl(_workload(count=8, seed=12), out,
              ServiceConfig(threads=4, watchdog_interval_s=0.05))
    assert len(out.getvalue().strip().splitlines()) == 8
    after = HEALTH.snapshot()
    assert after["worker_restarts"] == before["worker_restarts"]
    assert after["stuck_restarts"] == before["stuck_restarts"]


# -- completeness: every registered seam is chaos-tested -----------------------


def test_every_registered_seam_is_exercised():
    """A seam without a chaos test is untested recovery machinery: the
    union of seams covered above — plus the fleet suite's (imported, so
    a renamed or deleted fleet chaos test breaks THIS guard, not just
    its own file) — must BE the registry's seam set."""
    from test_fleet_chaos import FLEET_CHAOS_SEAMS

    covered = {seam for _, seam in SOLVER_SPECS}
    covered |= {seam for _, seam in BALANCE_SPECS}
    covered |= {spec.split(":", 1)[0] for spec, _ in SERVE_SPECS}
    covered |= set(FLEET_CHAOS_SEAMS)
    assert covered == set(faults.SEAMS), (
        f"uncovered seams: {set(faults.SEAMS) - covered}"
    )

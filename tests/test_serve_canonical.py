"""Property tests for canonical instance keys (serve.canonical).

The cache-key contract: translation of the whole instance, permutation of
the city list, and float jitter below half the quantization step must all
map to the SAME key; genuinely different instances must not collide. The
sort permutation must relabel tours correctly in both directions.
"""

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.serve.canonical import (
    canonicalize,
    from_canonical_tour,
    to_canonical_tour,
    tour_length_np,
)

pytestmark = pytest.mark.serve

STEP = 1e-3


def _grid_instance(rng, n, step=STEP):
    """Random instance with coordinates ON the quantization grid (multiples
    of 10*step), so jitter/translation margins are exact."""
    return rng.integers(0, 100_000, (n, 2)).astype(np.float64) * (10 * step)


@pytest.mark.parametrize("n", [3, 8, 17, 64])
def test_translation_invariance(n):
    rng = np.random.default_rng(n)
    xy = _grid_instance(rng, n)
    base = canonicalize(xy, STEP)
    for trial in range(20):
        # arbitrary real-valued translations: with on-grid coordinates the
        # common shift rounds identically for every city
        t = rng.uniform(-5_000.0, 5_000.0, (1, 2))
        assert canonicalize(xy + t, STEP).key == base.key, f"trial {trial}"


@pytest.mark.parametrize("n", [3, 8, 17, 64])
def test_permutation_invariance(n):
    rng = np.random.default_rng(100 + n)
    xy = _grid_instance(rng, n)
    base = canonicalize(xy, STEP)
    for trial in range(20):
        perm = rng.permutation(n)
        assert canonicalize(xy[perm], STEP).key == base.key, f"trial {trial}"


@pytest.mark.parametrize("n", [3, 8, 17, 64])
def test_jitter_below_half_step_invariance(n):
    rng = np.random.default_rng(200 + n)
    xy = _grid_instance(rng, n)
    base = canonicalize(xy, STEP)
    for trial in range(20):
        jitter = rng.uniform(-0.49 * STEP, 0.49 * STEP, xy.shape)
        assert canonicalize(xy + jitter, STEP).key == base.key, f"trial {trial}"


def test_combined_translation_permutation_jitter():
    rng = np.random.default_rng(7)
    xy = _grid_instance(rng, 23)
    base = canonicalize(xy, STEP)
    for trial in range(50):
        # translation by grid multiples composes exactly with sub-half-step
        # jitter; permutation is free
        t = rng.integers(-10_000, 10_000, (1, 2)) * STEP
        jitter = rng.uniform(-0.25 * STEP, 0.25 * STEP, xy.shape)
        perm = rng.permutation(23)
        assert canonicalize((xy + t + jitter)[perm], STEP).key == base.key


def test_distinct_instances_do_not_collide():
    rng = np.random.default_rng(11)
    keys = set()
    for _ in range(300):
        n = int(rng.integers(3, 30))
        keys.add(canonicalize(rng.uniform(0, 1000, (n, 2)), STEP).key)
    assert len(keys) == 300, "canonical keys collided across random instances"


def test_moving_one_city_changes_key():
    rng = np.random.default_rng(13)
    xy = _grid_instance(rng, 12)
    base = canonicalize(xy, STEP)
    moved = xy.copy()
    moved[5] += 10 * STEP  # one city, one grid cell over
    assert canonicalize(moved, STEP).key != base.key


def test_scaling_changes_key():
    # scaling is NOT an invariance (distances change) — keys must differ
    rng = np.random.default_rng(17)
    xy = _grid_instance(rng, 9)
    assert canonicalize(xy * 2.0, STEP).key != canonicalize(xy, STEP).key


def test_tour_relabel_roundtrip():
    rng = np.random.default_rng(19)
    xy = rng.uniform(0, 1000, (10, 2))
    ci = canonicalize(xy, STEP)
    tour = np.asarray(list(rng.permutation(10)) + [0], np.int32)
    tour[-1] = tour[0]  # closed
    canon_t = to_canonical_tour(tour, ci)
    back = from_canonical_tour(canon_t, ci)
    np.testing.assert_array_equal(back, tour)


def test_cached_tour_transfers_across_permutation():
    """The serving property the maps exist for: a tour cached in canonical
    ids, relabeled into a permuted resubmission, visits the same points in
    the same order (same true length)."""
    rng = np.random.default_rng(23)
    n = 12
    xy = rng.uniform(0, 1000, (n, 2))
    ci = canonicalize(xy, STEP)
    tour = np.asarray(list(rng.permutation(n)) + [0], np.int64)
    tour[-1] = tour[0]
    canon_t = to_canonical_tour(tour, ci)

    perm = rng.permutation(n)
    xy2 = xy[perm] + 50.0
    ci2 = canonicalize(xy2, STEP)
    tour2 = from_canonical_tour(canon_t, ci2)
    assert np.isclose(
        tour_length_np(tour, xy), tour_length_np(tour2, xy2), rtol=0, atol=1e-9
    )


def test_validation_errors():
    with pytest.raises(ValueError):
        canonicalize(np.zeros((0, 2)))
    with pytest.raises(ValueError):
        canonicalize(np.zeros((4, 3)))
    with pytest.raises(ValueError):
        canonicalize(np.asarray([[np.nan, 0.0]]))
    with pytest.raises(ValueError):
        canonicalize(np.zeros((4, 2)), step=0.0)

"""End-to-end pipeline vs golden final costs (the oracle's reported line)."""

import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models.pipeline import run_pipeline

FAST_CONFIGS = [
    "full_10x6_500x500.json",
    "full_5x10_1000x1000.json",
    "full_6x15_1000x1000.json",
    "full_5x50_1000x1000.json",
    "full_3x7_100x100.json",
    "full_4x9_1000x1000.json",
    "full_10x10_123x457.json",
    "full_13x4_1000x1000.json",
    "full_16x2_1000x1000.json",
    "full_10x100_1000x1000.json",
]

SLOW_CONFIGS = [
    "full_10x200_1000x1000.json",
    "full_12x100_1000x1000.json",
    "full_14x100_1000x1000.json",
    "full_16x100_1000x1000.json",
    "full_16x200_1000x1000.json",
]


def run_one(goldens_dir, name):
    g = json.loads((goldens_dir / name).read_text())
    cfg = g["config"]
    res = run_pipeline(cfg["ncpb"], cfg["nblocks"], cfg["gx"], cfg["gy"])
    assert res.cost == g["final"]["cost"], f"{res.cost!r} != {g['final']['cost']!r}"
    np.testing.assert_array_equal(res.tour_ids, g["final"]["ids"])
    assert res.num_cities == cfg["ncpb"] * cfg["nblocks"]


@pytest.mark.parametrize("name", FAST_CONFIGS)
def test_pipeline_bit_exact(goldens_dir, name):
    run_one(goldens_dir, name)


@pytest.mark.slow
@pytest.mark.parametrize("name", SLOW_CONFIGS)
def test_pipeline_bit_exact_slow(goldens_dir, name):
    run_one(goldens_dir, name)


def test_known_make_run_cost(goldens_dir):
    # `make run` config (Makefile:20): cost 3720.557435 printed by the oracle
    res = run_pipeline(10, 6, 500, 500)
    assert f"{res.cost:f}" == "3720.557435"


def test_rejects_degenerate_blocks():
    with pytest.raises(ValueError):
        run_pipeline(2, 4, 100, 100)
    with pytest.raises(ValueError):
        run_pipeline(1, 4, 100, 100)
    with pytest.raises(ValueError):
        run_pipeline(5, 0, 100, 100)


def test_phase_timings_present():
    res = run_pipeline(5, 10, 1000, 1000)
    assert set(res.phase_seconds) == {"generate", "distances", "solve", "merge_fold"}
    assert res.dp_transitions > 0

"""Held-Karp 1-tree bound: MST correctness, bound validity, B&B speedup."""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops.one_tree import (
    held_karp_potentials,
    mst_cost_degrees,
    one_tree_cost_degrees,
)
from tsp_mpi_reduction_tpu.utils.tsplib import burma14


def _prim_reference(d: np.ndarray) -> float:
    """Independent host Prim (different code path from the jax fori_loop)."""
    m = d.shape[0]
    in_tree = {0}
    cost = 0.0
    while len(in_tree) < m:
        best = min(
            ((d[i, j], j) for i in in_tree for j in range(m) if j not in in_tree),
        )
        cost += best[0]
        in_tree.add(best[1])
    return cost


def _random_metric(n: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    xy = rng.uniform(0, 100, (n, 2))
    d = np.hypot(*(xy[:, None, :] - xy[None, :, :]).transpose(2, 0, 1))
    return d


@pytest.mark.parametrize("m,seed", [(4, 0), (7, 1), (12, 2), (20, 3)])
def test_mst_matches_reference_prim(m, seed):
    d = _random_metric(m, seed)
    dj = jnp.asarray(np.where(np.eye(m, dtype=bool), np.inf, d), jnp.float64)
    cost, deg = mst_cost_degrees(dj)
    assert float(cost) == pytest.approx(_prim_reference(d), rel=1e-12)
    assert int(deg.sum()) == 2 * (m - 1)  # tree has m-1 edges


def test_one_tree_has_n_edges_and_degree_two_at_root():
    n = 9
    d = _random_metric(n, 4)
    dj = jnp.asarray(np.where(np.eye(n, dtype=bool), np.inf, d), jnp.float64)
    cost, deg = one_tree_cost_degrees(dj)
    assert int(deg[0]) == 2
    assert int(deg.sum()) == 2 * n  # n edges total
    # 1-tree with pi=0 lower-bounds the optimal tour (brute force, n small)
    best = min(
        sum(d[p[i], p[i + 1]] for i in range(n - 1)) + d[p[-1], p[0]]
        for p in itertools.permutations(range(1, n))
        for p in [(0,) + p]
    )
    assert float(cost) <= best + 1e-9


@pytest.mark.parametrize("n,seed", [(8, 5), (10, 6)])
def test_potentials_tighten_but_stay_valid(n, seed):
    d = _random_metric(n, seed)
    dj = jnp.asarray(d, jnp.float64)
    pi, lb = held_karp_potentials(dj, steps=100)
    d_inf = jnp.asarray(np.where(np.eye(n, dtype=bool), np.inf, d), jnp.float64)
    plain, _ = one_tree_cost_degrees(d_inf)
    best = min(
        sum(d[p[i], p[i + 1]] for i in range(n - 1)) + d[p[-1], p[0]]
        for p in itertools.permutations(range(1, n))
        for p in [(0,) + p]
    )
    assert float(lb) <= best + 1e-6  # valid
    assert float(lb) >= float(plain) - 1e-9  # at least the pi=0 value


def test_bound_setup_zero_pi_reduces_to_min_out():
    d = _random_metric(6, 7)
    bd = bb._bound_setup(d, "min-out")
    min_out = np.where(np.eye(6, dtype=bool), np.inf, d).min(1)
    np.testing.assert_allclose(np.asarray(bd.min_out), min_out, rtol=1e-6)
    # float path: the rounding slack is shaved off the (otherwise zero) adj
    np.testing.assert_allclose(
        np.asarray(bd.bound_adj), -float(bd.slack) * np.ones(6), rtol=1e-6
    )
    assert not bd.integral  # random float metric takes the slack path
    assert float(bd.slack) > 0.0


def test_burma14_one_tree_bound_is_tight():
    d = burma14().distance_matrix()
    pi, lb = held_karp_potentials(jnp.asarray(d, jnp.float32), steps=150)
    # burma14 optimum is 3323; the HK bound is famously within ~1%
    assert 3200.0 <= float(lb) <= 3323.0 + 1e-3


def test_bnb_one_tree_matches_min_out_and_prunes_harder():
    d = burma14().distance_matrix()
    r_mo = bb.solve(d, capacity=1 << 15, k=64, inner_steps=8, bound="min-out")
    r_ot = bb.solve(d, capacity=1 << 15, k=64, inner_steps=8, bound="one-tree")
    assert r_mo.proven_optimal and r_ot.proven_optimal
    assert round(r_mo.cost) == round(r_ot.cost) == 3323
    assert r_ot.nodes_expanded < r_mo.nodes_expanded
    assert r_ot.root_lower_bound > 3200.0


def test_checkpoint_refuses_other_bound(tmp_path):
    d = _random_metric(9, 8)
    ck = str(tmp_path / "ck.npz")
    bb.solve(d, capacity=1 << 10, k=16, inner_steps=2, max_iters=2,
             checkpoint_path=ck, bound="one-tree")
    with pytest.raises(ValueError, match="bound"):
        bb.solve(d, capacity=1 << 10, k=16, inner_steps=2,
                 resume_from=ck, bound="min-out")

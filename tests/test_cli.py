"""CLI compat surface: stdout format, exit codes, flags (in-process)."""

import pytest

from tsp_mpi_reduction_tpu.utils import reporting
from tsp_mpi_reduction_tpu.utils.cli import main


def run_cli(capsys, argv):
    code = main(argv)
    out = capsys.readouterr()
    return code, out.out, out.err


def test_final_line_format_matches_oracle(capsys):
    code, out, _ = run_cli(capsys, ["10", "6", "500", "500", "--backend=cpu"])
    assert code == 0
    lines = out.strip().split("\n")
    assert lines[0] == "We have 10 cities for each of our 6 blocks"
    assert lines[1] == "2 blocks in X 3 in Y"
    # oracle-identical cost text (golden: make-run config, cost 3720.557435)
    assert lines[2].startswith("TSP ran in ")
    assert lines[2].endswith(" ms for 60 cities and the trip cost 3720.557435")


def test_wrong_arity_usage_exit1(capsys):
    code, out, _ = run_cli(capsys, ["10", "6"])
    assert code == 1
    assert out.strip() == reporting.usage_line()


def test_seventeen_cities_exit_1337(capsys):
    with pytest.raises(SystemExit) as e:
        main(["17", "6", "500", "500"])
    assert e.value.code == 1337  # OS truncates to 57, like the reference
    assert "retry that with less than 16" in capsys.readouterr().out


def test_degenerate_blocks_exit2(capsys):
    code, _, err = run_cli(capsys, ["2", "6", "500", "500", "--backend=cpu"])
    assert code == 2
    assert "3 cities" in err


def test_ranks_flag_changes_merge_order(capsys):
    code1, out1, _ = run_cli(capsys, ["5", "10", "500", "500", "--backend=cpu"])
    code2, out2, _ = run_cli(
        capsys, ["5", "10", "500", "500", "--backend=cpu", "--ranks=4"]
    )
    assert code1 == code2 == 0
    cost1 = out1.strip().split()[-1]
    cost2 = out2.strip().split()[-1]
    assert cost1 != cost2  # non-associative operator, different tree


def test_compat_bugs_flag_changes_multirank_cost(capsys):
    """--compat-bugs (quirk #5 emulation) must alter the multi-rank result
    (any rank receiving twice merges a corrupted operand) while leaving
    p<=2 trees — where no rank receives twice past the downshift — intact
    relative to its own deterministic output."""
    args = ["5", "8", "300", "300", "--backend=cpu", "--ranks=4"]
    code1, out1, _ = run_cli(capsys, args)
    code2, out2, _ = run_cli(capsys, args + ["--compat-bugs"])
    assert code1 == code2 == 0
    assert out1.strip().split()[-1] != out2.strip().split()[-1]
    # deterministic: same flag, same output
    code3, out3, _ = run_cli(capsys, args + ["--compat-bugs"])
    assert out3.strip().split()[-1] == out2.strip().split()[-1]


def test_metrics_flag_emits_json(capsys):
    import json

    code, _, err = run_cli(
        capsys, ["5", "10", "500", "500", "--backend=cpu", "--metrics"]
    )
    assert code == 0
    m = json.loads(err.strip().split("\n")[-1])
    assert m["config"]["numBlocks"] == 10
    assert m["cost"] > 0


def test_select_backend_auto_dead_grant_falls_back(monkeypatch):
    """--backend=auto with a registered remote plugin whose claim handshake
    hangs (mocked via a sleeping probe subprocess) must fall back to CPU
    within the probe timeout instead of hanging forever (VERDICT r4 weak #1:
    bnb_solve sat >300 s on a dead grant)."""
    import os
    import time

    from tsp_mpi_reduction_tpu.utils import backend

    monkeypatch.setattr(backend, "_PROBE_CODE", "import time; time.sleep(60)")
    monkeypatch.setattr(
        backend, "_registered_platforms", lambda: {"cpu", "tpu", "axon"}
    )
    monkeypatch.setenv("TSP_BACKEND_PROBE_TIMEOUT", "2")
    monkeypatch.delenv("TSP_BACKEND_PROBED", raising=False)
    # un-pin the conftest's JAX_PLATFORMS=cpu so auto actually considers
    # the (mock) remote accelerator rather than short-circuiting
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    t0 = time.monotonic()
    try:
        assert backend.select_backend("auto") == "cpu"
    finally:
        os.environ.pop("TSP_BACKEND_PROBED", None)
    assert time.monotonic() - t0 < 30  # bounded, not the infinite hang


def test_select_backend_tpu_dead_grant_raises(monkeypatch):
    """--backend=tpu on a dead remote grant must raise cleanly (bounded by
    the probe timeout), never enter the unguarded in-process init."""
    import os

    import pytest

    from tsp_mpi_reduction_tpu.utils import backend

    monkeypatch.setattr(backend, "_PROBE_CODE", "import time; time.sleep(60)")
    monkeypatch.setattr(
        backend, "_registered_platforms", lambda: {"cpu", "tpu", "axon"}
    )
    monkeypatch.setenv("TSP_BACKEND_PROBE_TIMEOUT", "2")
    monkeypatch.delenv("TSP_BACKEND_PROBED", raising=False)
    try:
        with pytest.raises(RuntimeError, match="no accelerator platform"):
            backend.select_backend("tpu")
    finally:
        os.environ.pop("TSP_BACKEND_PROBED", None)


def test_accelerator_probe_accepts_only_noncpu_platforms(monkeypatch):
    """The probe is platform-aware: a subprocess that comes up CPU-only
    (e.g. grant lapsed between registration and init) is not 'usable'."""
    import os

    from tsp_mpi_reduction_tpu.utils import backend

    monkeypatch.delenv("TSP_BACKEND_PROBED", raising=False)
    monkeypatch.setattr(backend, "_PROBE_CODE", "print('PLATFORM=cpu')")
    assert not backend.accelerator_usable(timeout_s=30)
    monkeypatch.setattr(backend, "_PROBE_CODE", "print('PLATFORM=axon')")
    try:
        assert backend.accelerator_usable(timeout_s=30)
        assert os.environ.get("TSP_BACKEND_PROBED") == "1"  # children skip
    finally:
        os.environ.pop("TSP_BACKEND_PROBED", None)


def test_select_backend_tpu_detects_initialized_cpu_backend():
    """A cached CPU backend must not masquerade as a TPU (phantom-accelerator
    guard in select_backend's probe loop)."""
    import jax
    import jax.numpy as jnp
    import pytest

    from tsp_mpi_reduction_tpu.utils import backend

    _ = jnp.zeros(1) + 1  # ensure the (conftest-pinned) CPU backend is live
    if "tpu" not in backend._registered_platforms():
        pytest.skip("no tpu factory registered")
    prev = jax.config.jax_platforms
    with pytest.raises(RuntimeError, match="no accelerator platform"):
        backend.select_backend("tpu")
    assert jax.config.jax_platforms == prev  # config restored on failure

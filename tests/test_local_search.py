"""Device 2-opt kernel + ring sequence-parallel improver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models.branch_bound import (
    nearest_neighbor_tour,
    two_opt as host_two_opt,
    tour_cost,
)
from tsp_mpi_reduction_tpu.ops.local_search import (
    tour_length,
    two_opt_batch,
    two_opt_sweep,
)
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
from tsp_mpi_reduction_tpu.parallel.seq_improve import improve_tour, ring_two_opt


def _metric(n, seed):
    rng = np.random.default_rng(seed)
    xy = rng.uniform(0, 1000, (n, 2))
    return np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1))


@pytest.mark.parametrize("n,seed", [(12, 0), (30, 1), (64, 2)])
def test_two_opt_sweep_improves_and_preserves_permutation(n, seed):
    d = _metric(n, seed)
    dj = jnp.asarray(d)
    t0 = jnp.asarray(np.random.default_rng(seed).permutation(n), jnp.int32)
    before = float(tour_length(t0, dj))
    t1, delta = two_opt_sweep(t0, dj)
    after = float(tour_length(t1, dj))
    assert sorted(np.asarray(t1).tolist()) == list(range(n))
    assert after <= before + 1e-6
    assert after == pytest.approx(before + float(delta), rel=1e-9)


def test_two_opt_sweep_matches_host_quality():
    """Device best-improvement 2-opt should land at the same local optimum
    as the host reference implementation from the same start."""
    d = _metric(24, 3)
    start = nearest_neighbor_tour(d)  # closed [n+1]
    host = host_two_opt(d, start)
    dev, _ = two_opt_sweep(jnp.asarray(start[:-1], jnp.int32), jnp.asarray(d))
    assert float(tour_length(dev, jnp.asarray(d))) == pytest.approx(
        tour_cost(d, host), rel=1e-9
    )


def test_two_opt_open_path_pins_endpoints():
    d = _metric(16, 4)
    t0 = jnp.asarray(np.random.default_rng(4).permutation(16), jnp.int32)
    t1, _ = two_opt_sweep(t0, jnp.asarray(d), closed=False)
    assert int(t1[0]) == int(t0[0]) and int(t1[-1]) == int(t0[-1])
    assert float(tour_length(t1, jnp.asarray(d), closed=False)) <= float(
        tour_length(t0, jnp.asarray(d), closed=False)
    ) + 1e-6


def test_two_opt_batch_vmaps():
    d = _metric(20, 5)
    rng = np.random.default_rng(5)
    tours = jnp.asarray(
        np.stack([rng.permutation(20) for _ in range(6)]), jnp.int32
    )
    out, deltas = two_opt_batch(tours, jnp.asarray(d))
    assert out.shape == tours.shape
    for i in range(6):
        assert sorted(np.asarray(out[i]).tolist()) == list(range(20))
        assert float(deltas[i]) <= 1e-6


def test_ring_two_opt_on_8_rank_mesh():
    n = 128
    d = _metric(n, 6)
    dj = jnp.asarray(d)
    mesh = make_rank_mesh(8)
    t0 = jnp.asarray(np.random.default_rng(6).permutation(n), jnp.int32)
    before = float(tour_length(t0, dj))
    t1 = ring_two_opt(t0, dj, mesh)
    after = float(tour_length(t1, dj))
    assert sorted(np.asarray(t1).tolist()) == list(range(n))
    assert after < before  # random tour must improve
    # should be comparable to a plain single-device sweep from the same start
    single, _ = two_opt_sweep(t0, dj)
    assert after <= float(tour_length(single, dj)) * 1.15


def test_improve_tour_single_and_mesh_agree_on_validity():
    n = 96
    d = _metric(n, 7)
    dj = jnp.asarray(d)
    t0 = jnp.asarray(np.random.default_rng(7).permutation(n), jnp.int32)
    for mesh in (None, make_rank_mesh(8)):
        order, length = improve_tour(t0, dj, mesh)
        assert sorted(np.asarray(order).tolist()) == list(range(n))
        assert float(length) == pytest.approx(
            float(tour_length(order, dj)), rel=1e-9
        )


def test_ring_two_opt_rejects_bad_shapes():
    d = jnp.asarray(_metric(30, 8))
    mesh = make_rank_mesh(8)
    with pytest.raises(ValueError, match="divisible"):
        ring_two_opt(jnp.arange(30, dtype=jnp.int32), d, mesh)


def test_strong_incumbent_beats_or_matches_single_start():
    from tsp_mpi_reduction_tpu.models.branch_bound import (
        strong_incumbent,
        tour_cost,
        two_opt,
    )

    d = _metric(40, 9)
    multi = strong_incumbent(d, starts=8)
    single = host_two_opt(d, nearest_neighbor_tour(d))
    assert multi[0] == multi[-1] == 0
    assert sorted(multi[:-1].tolist()) == list(range(40))
    assert tour_cost(d, multi) <= tour_cost(d, single) + 1e-9


def test_cli_improve_reports_true_cost_of_polished_tour(capsys):
    """--improve's printed cost must equal the true length of the polished
    tour, which improve_tour guarantees is <= the true length of the input
    tour. (The unflagged run's formulaic merge cost is NOT comparable —
    SURVEY.md quirk #4 — so no ordering vs it is asserted.)"""
    from tsp_mpi_reduction_tpu.models.pipeline import run_pipeline
    from tsp_mpi_reduction_tpu.utils.cli import main

    code = main(["5", "8", "400", "400", "--backend=cpu", "--improve"])
    improved = float(capsys.readouterr().out.strip().split()[-1])
    assert code == 0
    res = run_pipeline(5, 8, 400, 400)
    true_base = float(
        tour_length(jnp.asarray(res.tour_ids[:-1], jnp.int32), res.dist)
    )
    assert improved <= true_base + 1e-6


def test_or_opt_sweep_improves_and_preserves_permutation():
    from tsp_mpi_reduction_tpu.ops.local_search import or_opt_sweep

    for n, seed in [(14, 10), (40, 11)]:
        d = _metric(n, seed)
        dj = jnp.asarray(d)
        t0 = jnp.asarray(np.random.default_rng(seed).permutation(n), jnp.int32)
        t1, delta = or_opt_sweep(t0, dj)
        assert sorted(np.asarray(t1).tolist()) == list(range(n))
        assert float(tour_length(t1, dj)) == pytest.approx(
            float(tour_length(t0, dj)) + float(delta), rel=1e-6
        )
        assert float(delta) <= 1e-6


def test_or_opt_delta_matches_brute_force_relocation():
    """Every finite (L, i, j) delta equals the measured cost change."""
    from tsp_mpi_reduction_tpu.ops.local_search import (
        _apply_relocation,
        _relocation_deltas,
    )

    n = 9
    d = _metric(n, 12)
    dj = jnp.asarray(d)
    t = jnp.asarray(np.random.default_rng(12).permutation(n), jnp.int32)
    base = float(tour_length(t, dj))
    for L in (1, 2, 3):
        deltas = np.asarray(_relocation_deltas(t, dj, L))
        for i in range(n):
            for j in range(n):
                if not np.isfinite(deltas[i, j]):
                    continue
                moved = _apply_relocation(t, i, L, j)
                assert sorted(np.asarray(moved).tolist()) == list(range(n)), (
                    L, i, j,
                )
                got = float(tour_length(moved, dj)) - base
                assert got == pytest.approx(deltas[i, j], abs=1e-6), (L, i, j)


def test_polish_at_least_as_good_as_two_opt():
    from tsp_mpi_reduction_tpu.ops.local_search import polish

    d = _metric(48, 13)
    dj = jnp.asarray(d)
    t0 = jnp.asarray(np.random.default_rng(13).permutation(48), jnp.int32)
    t2, _ = two_opt_sweep(t0, dj)
    tp, _ = polish(t0, dj)
    assert sorted(np.asarray(tp).tolist()) == list(range(48))
    assert float(tour_length(tp, dj)) <= float(tour_length(t2, dj)) + 1e-6

"""Pallas relaxation kernel: bit-parity vs the jnp path (interpret on CPU)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tsp_mpi_reduction_tpu.ops import held_karp
from tsp_mpi_reduction_tpu.ops.held_karp_pallas import relax_minplus, relax_reference


@pytest.mark.parametrize("m", [4, 9, 15, 17])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_relax_matches_reference(m, dtype):
    rng = np.random.default_rng(m)
    j = 130  # not a multiple of the row tile: exercises padding
    g = rng.uniform(0, 100, (j, m)).astype(dtype)
    g[rng.uniform(size=(j, m)) < 0.2] = np.inf  # masked-out predecessors
    g[3] = np.inf  # an all-inf row (no valid predecessor): stays inf, parent 0
    d_t = rng.uniform(0, 50, (m, m)).astype(dtype)

    ref_c, ref_p = relax_reference(jnp.asarray(g), jnp.asarray(d_t))
    got_c, got_p = relax_minplus(jnp.asarray(g), jnp.asarray(d_t), interpret=True)
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(ref_c))
    np.testing.assert_array_equal(np.asarray(got_p), np.asarray(ref_p))


@pytest.mark.parametrize("n", [6, 10])
def test_full_solve_pallas_matches_jnp(n):
    """End-to-end DP with the kernel == the jnp path, bit for bit."""
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 500, (4, n, 2))
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np

    d = jnp.asarray(distance_matrix_np(xy))
    with held_karp.use_impl("jnp"):
        c_ref, t_ref = held_karp.solve_blocks_from_dists(d, jnp.float64)
    with held_karp.use_impl("pallas"):
        c_got, t_got = held_karp.solve_blocks_from_dists(d, jnp.float64)
    np.testing.assert_array_equal(np.asarray(c_got), np.asarray(c_ref))
    np.testing.assert_array_equal(np.asarray(t_got), np.asarray(t_ref))


def test_auto_policy_is_compact():
    assert held_karp._effective_impl(jnp.float64) == "compact"
    assert held_karp._effective_impl(jnp.float32) == "compact"


@pytest.mark.parametrize("n", [5, 10, 13])
def test_fused_pallas_matches_compact(n):
    """Fused dense kernel + parent-free backtrack == compact, bit for bit."""
    rng = np.random.default_rng(n)
    xy = rng.uniform(0, 500, (3, n, 2))
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np

    d = jnp.asarray(distance_matrix_np(xy))
    with held_karp.use_impl("compact"):
        c_ref, t_ref = held_karp.solve_blocks_from_dists(d, jnp.float64)
    with held_karp.use_impl("fused"):
        c_got, t_got = held_karp.solve_blocks_from_dists(d, jnp.float64)
    np.testing.assert_array_equal(np.asarray(c_got), np.asarray(c_ref))
    np.testing.assert_array_equal(np.asarray(t_got), np.asarray(t_ref))


@pytest.mark.parametrize("n", [5, 8, 12])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_dense_sweep_matches_compact(n, dtype):
    """The dense bit-swap formulation is bit-identical to the compacted DP."""
    rng = np.random.default_rng(n)
    xy = rng.uniform(0, 500, (5, n, 2))
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np

    d = jnp.asarray(distance_matrix_np(xy), dtype)
    with held_karp.use_impl("compact"):
        c_ref, t_ref = held_karp.solve_blocks_from_dists(d, dtype)
    with held_karp.use_impl("dense"):
        c_got, t_got = held_karp.solve_blocks_from_dists(d, dtype)
    np.testing.assert_array_equal(np.asarray(c_got), np.asarray(c_ref))
    np.testing.assert_array_equal(np.asarray(t_got), np.asarray(t_ref))


def test_dense_sweep_matches_golden_solutions(goldens_dir):
    """Dense impl reproduces oracle block solutions bit-for-bit (f64)."""
    import json

    golden = json.loads((goldens_dir / "full_10x6_500x500.json").read_text())
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np

    xy = np.asarray(
        [[[c[1], c[2]] for c in blk] for blk in golden["blocks"]]
    )
    d = jnp.asarray(distance_matrix_np(xy))
    with held_karp.use_impl("dense"):
        costs, tours = held_karp.solve_blocks_from_dists(d, jnp.float64)
    n = xy.shape[1]
    for b, sol in enumerate(golden["block_solutions"]):
        assert float(costs[b]) == sol["cost"]
        assert (np.asarray(tours[b]) + b * n).tolist() == sol["ids"]

"""Instance generator vs golden coordinates captured from the reference."""

import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.ops.generator import (
    generate_instance,
    get_blocks_per_dim,
    is_square,
)

CONFIGS = [
    "full_10x6_500x500.json",
    "full_5x10_1000x1000.json",
    "full_6x15_1000x1000.json",
    "full_5x50_1000x1000.json",  # grid-spill: 50 blocks -> 2x25 dims
    "full_3x7_100x100.json",  # prime block count -> 7x1
    "full_4x9_1000x1000.json",  # perfect square -> 3x3
    "full_10x10_123x457.json",  # non-square grid dims
    "full_13x4_1000x1000.json",
    "full_16x2_1000x1000.json",
    "full_10x100_1000x1000.json",
]


@pytest.mark.parametrize("name", CONFIGS)
def test_coords_bit_exact(goldens_dir, name):
    g = json.loads((goldens_dir / name).read_text())
    cfg = g["config"]
    rows, cols = get_blocks_per_dim(cfg["nblocks"])
    assert [rows, cols] == g["dims"]
    ids, xy = generate_instance(cfg["ncpb"], cfg["nblocks"], cfg["gx"], cfg["gy"])
    gold = np.asarray(g["blocks"], dtype=np.float64)  # [B, n, 3] = id, x, y
    np.testing.assert_array_equal(ids, gold[:, :, 0].astype(np.int32))
    # bit-exact: zero tolerance
    np.testing.assert_array_equal(xy[:, :, 0], gold[:, :, 1])
    np.testing.assert_array_equal(xy[:, :, 1], gold[:, :, 2])


def test_grid_spill_quirk(goldens_dir):
    # 50 blocks factor as 2x25; x coordinates must spill far beyond gridDimX
    _, xy = generate_instance(5, 50, 1000, 1000)
    assert xy[:, :, 0].max() > 10000  # 25 * (1000/2) = 12500 nominal max
    assert xy[:, :, 1].max() <= 1000 + 1e-9


def test_blocks_per_dim_factorizations():
    assert get_blocks_per_dim(9) == (3, 3)
    assert get_blocks_per_dim(6) == (2, 3)
    assert get_blocks_per_dim(15) == (3, 5)
    assert get_blocks_per_dim(7) == (7, 1)  # prime -> p x 1
    assert get_blocks_per_dim(50) == (2, 25)
    assert is_square(16) and not is_square(15)

"""Deadline ladder + end-to-end service tests (serve.ladder / serve.service)."""

import io
import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.serve.ladder import (
    DeadlineLadder,
    LadderConfig,
    LatencyEstimator,
    _largest_block_divisor,
)
from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler
from tsp_mpi_reduction_tpu.serve.service import (
    ServiceConfig,
    SolveService,
    run_jsonl,
)

pytestmark = pytest.mark.serve


def _valid_closed_tour(tour, n):
    tour = list(tour)
    return tour[0] == tour[-1] and sorted(tour[:-1]) == list(range(n))


# -- ladder ------------------------------------------------------------------


def _ladder(**cfg_kw):
    sched = MicroBatchScheduler(max_batch=8, max_wait_ms=1.0, buckets=(1, 2, 4, 8))
    return DeadlineLadder(sched, LadderConfig(**cfg_kw)), sched


def test_impossible_deadline_answers_greedy():
    ladder, sched = _ladder()
    with sched:
        rng = np.random.default_rng(0)
        xy = rng.uniform(0, 1000, (9, 2))
        res = ladder.solve(xy, deadline_s=0.0)
    assert res.tier == "greedy"
    assert _valid_closed_tour(res.tour, 9)
    assert res.certified_gap is None
    assert ladder.tier_counts["greedy"] == 1


def test_generous_deadline_uses_exact_pipeline():
    ladder, sched = _ladder(bnb_max_n=0)  # bnb rung disabled -> pipeline
    with sched:
        rng = np.random.default_rng(1)
        xy = rng.uniform(0, 1000, (8, 2))
        res = ladder.solve(xy, deadline_s=120.0)
    assert res.tier == "pipeline"
    assert res.certified_gap == 0.0  # single-block Held-Karp is exact
    assert _valid_closed_tour(res.tour, 8)


def test_bnb_rung_selected_and_certified():
    calls = {}

    def fake_bnb(d, time_limit_s):
        calls["limit"] = time_limit_s
        n = d.shape[0]
        tour = np.asarray(list(range(n)) + [0], np.int32)
        cost = float(d[tour[:-1], tour[1:]].sum())
        return cost, tour, cost, True  # proven

    ladder, sched = _ladder(bnb_solver=fake_bnb, bnb_min_budget_s=0.1)
    ladder.estimator.observe("bnb", 8, 0.01)  # teach: bnb is cheap here
    with sched:
        rng = np.random.default_rng(2)
        res = ladder.solve(rng.uniform(0, 1000, (8, 2)), deadline_s=30.0)
    assert res.tier == "bnb"
    assert res.certified_gap == 0.0
    assert 0 < calls["limit"] < 30.0  # budget fraction applied


def test_bnb_unproven_reports_certified_gap():
    def fake_bnb(d, time_limit_s):
        n = d.shape[0]
        tour = np.asarray(list(range(n)) + [0], np.int32)
        return 110.0, tour, 100.0, False  # cost 110, certified LB 100

    ladder, sched = _ladder(bnb_solver=fake_bnb, bnb_min_budget_s=0.1)
    ladder.estimator.observe("bnb", 8, 0.01)
    with sched:
        rng = np.random.default_rng(3)
        res = ladder.solve(rng.uniform(0, 1000, (8, 2)), deadline_s=30.0)
    assert res.tier == "bnb"
    assert res.certified_gap == pytest.approx(0.1)
    assert res.lower_bound == 100.0


def test_real_bnb_rung_proves_tiny_instance():
    ladder, sched = _ladder(
        bnb_min_budget_s=0.1, bnb_capacity=1 << 10, bnb_k=8
    )
    ladder.estimator.observe("bnb", 8, 0.01)
    with sched:
        rng = np.random.default_rng(4)
        xy = rng.uniform(0, 100, (7, 2))
        res = ladder.solve(xy, deadline_s=300.0)
    assert res.tier == "bnb"
    assert res.certified_gap == 0.0
    assert _valid_closed_tour(res.tour, 7)


def test_blocked_pipeline_large_instance():
    # n=24 > MAX_BLOCK_CITIES: blocked decomposition (b=12), merge, polish
    ladder, sched = _ladder(bnb_max_n=0, polish_rounds=2)
    with sched:
        rng = np.random.default_rng(5)
        xy = rng.uniform(0, 1000, (24, 2))
        res = ladder.solve(xy, deadline_s=300.0)
    assert res.tier == "pipeline"
    assert res.certified_gap is None  # heuristic rung: no certificate
    assert _valid_closed_tour(res.tour, 24)


def test_trivial_instances():
    ladder, sched = _ladder()
    with sched:
        r1 = ladder.solve(np.asarray([[1.0, 2.0]]), deadline_s=10.0)
        r2 = ladder.solve(np.asarray([[0.0, 0.0], [3.0, 4.0]]), deadline_s=10.0)
    assert list(r1.tour) == [0, 0] and r1.cost == 0.0
    assert list(r2.tour) == [0, 1, 0] and r2.cost == pytest.approx(10.0)


def test_largest_block_divisor():
    assert _largest_block_divisor(24) == 12
    assert _largest_block_divisor(32) == 16
    assert _largest_block_divisor(33) == 11
    assert _largest_block_divisor(23) is None  # prime > 16
    assert _largest_block_divisor(18) == 9


def test_latency_estimator_ewma():
    est = LatencyEstimator(alpha=0.5)
    assert est.estimate("bnb", 8, 5.0) == 5.0  # prior until observed
    est.observe("bnb", 8, 1.0)
    assert est.estimate("bnb", 8, 5.0) == 1.0
    est.observe("bnb", 8, 3.0)
    assert est.estimate("bnb", 8, 5.0) == pytest.approx(2.0)
    # bucketing: n=7 and n=8 share a bucket, n=9 does not
    assert est.estimate("bnb", 7, 9.0) == pytest.approx(2.0)
    assert est.estimate("bnb", 9, 9.0) == 9.0


# -- service -----------------------------------------------------------------


def _cfg(**kw):
    kw.setdefault("ladder", LadderConfig(bnb_max_n=0))
    kw.setdefault("max_wait_ms", 5.0)
    return ServiceConfig(**kw)


def test_service_miss_then_permuted_translated_hit():
    rng = np.random.default_rng(10)
    xy = rng.uniform(0, 1000, (8, 2))
    with SolveService(_cfg()) as svc:
        r1 = svc.handle({"id": "a", "xy": xy.tolist(), "deadline_ms": 60_000})
        dup = xy[rng.permutation(8)] + 123.0
        r2 = svc.handle({"id": "b", "xy": dup.tolist(), "deadline_ms": 60_000})
    assert r1["cache"] == "miss" and r2["cache"] == "hit"
    assert r2["tier"] == r1["tier"]
    assert _valid_closed_tour(r2["tour"], 8)
    # same geometry -> same measured cost (translation-invariant)
    assert r2["cost"] == pytest.approx(r1["cost"], rel=1e-9)


def test_service_tight_deadline_never_errors():
    with SolveService(_cfg()) as svc:
        rng = np.random.default_rng(11)
        for i in range(5):
            xy = rng.uniform(0, 1000, (10, 2))
            resp = svc.handle(
                {"id": i, "xy": xy.tolist(), "deadline_ms": 0.001}
            )
            assert "error" not in resp
            assert resp["tier"] == "greedy"
            assert _valid_closed_tour(resp["tour"], 10)
            assert resp["deadline_missed"] is True
        assert svc.deadline_misses == 5


def test_service_malformed_requests_get_error_responses():
    with SolveService(_cfg()) as svc:
        assert "error" in svc.handle({"id": 1})  # no xy
        assert "error" in svc.handle({"id": 2, "xy": [[1, 2, 3]]})  # bad shape
        assert "error" in svc.handle({"id": 3, "xy": "nope"})
        assert svc.errors == 3


def test_run_jsonl_order_and_stats():
    rng = np.random.default_rng(12)
    lines = []
    for i in range(6):
        xy = rng.uniform(0, 1000, (7, 2))
        lines.append(json.dumps(
            {"id": f"r{i}", "xy": xy.tolist(), "deadline_ms": 60_000}
        ))
    lines.insert(3, "not json{")
    lines.insert(5, json.dumps([1, 2, 3]))  # JSON but not an object
    out = io.StringIO()
    svc = run_jsonl(lines, out, _cfg(threads=4))
    rows = [json.loads(x) for x in out.getvalue().strip().splitlines()]
    assert len(rows) == 8
    # responses come back in INPUT order
    ids = [r.get("id") for r in rows]
    assert ids == ["r0", "r1", "r2", None, "r3", None, "r4", "r5"]
    assert "error" in rows[3] and "error" in rows[5]
    stats = json.loads(svc.stats_json())
    assert stats["responses"] == 6 and stats["errors"] == 2
    assert stats["tiers"]["pipeline"] == 6
    assert stats["cache"]["misses"] >= 6
    assert stats["scheduler"]["blocks_solved"] == 6
    assert "queue_depth_hwm" in stats["scheduler"]
    assert "batch_occupancy" in stats["scheduler"]


def test_service_cache_prefers_certified_entry():
    """A deadline-degraded greedy answer must not clobber a cached exact
    one, and a later hit returns the exact tier."""
    rng = np.random.default_rng(13)
    xy = rng.uniform(0, 1000, (8, 2))
    with SolveService(_cfg()) as svc:
        r1 = svc.handle({"id": "a", "xy": xy.tolist(), "deadline_ms": 60_000})
        assert r1["tier"] == "pipeline" and r1["certified_gap"] == 0.0
        # resubmit with an impossible deadline: the HIT serves the cached
        # exact answer without running any rung at all
        r2 = svc.handle({"id": "b", "xy": xy.tolist(), "deadline_ms": 0.001})
        assert r2["cache"] == "hit" and r2["tier"] == "pipeline"


def test_service_upgrades_cached_greedy_on_generous_budget():
    """Finding-3 regression: a greedy answer cached under an impossible
    deadline must NOT pin the instance — a later generous-budget request
    re-solves with a stronger rung ('refresh') and upgrades the cache."""
    rng = np.random.default_rng(20)
    xy = rng.uniform(0, 1000, (8, 2))
    with SolveService(_cfg()) as svc:
        r1 = svc.handle({"id": "a", "xy": xy.tolist(), "deadline_ms": 0.001})
        assert r1["tier"] == "greedy" and r1["cache"] == "miss"
        r2 = svc.handle({"id": "b", "xy": xy.tolist(), "deadline_ms": 60_000})
        assert r2["cache"] == "refresh"
        assert r2["tier"] == "pipeline" and r2["certified_gap"] == 0.0
        assert r2["cost"] <= r1["cost"] + 1e-9  # upgrade never serves worse
        # now exact is cached: a third request is a plain hit, no re-solve
        r3 = svc.handle({"id": "c", "xy": xy.tolist(), "deadline_ms": 60_000})
        assert r3["cache"] == "hit" and r3["tier"] == "pipeline"
        assert svc.refreshes == 1


def test_ladder_rung_exception_degrades_to_greedy():
    """Finding-1 regression: a rung that raises (device OOM, solver bug)
    must degrade like a timeout — the request still gets a valid tour and
    the stream never sees an exception."""

    def exploding_bnb(d, time_limit_s):
        raise MemoryError("synthetic device OOM")

    ladder, sched = _ladder(bnb_solver=exploding_bnb, bnb_min_budget_s=0.1)
    ladder.estimator.observe("bnb", 8, 0.01)
    with sched:
        rng = np.random.default_rng(21)
        res = ladder.solve(rng.uniform(0, 1000, (8, 2)), deadline_s=30.0)
    assert res.tier in ("pipeline", "greedy")  # degraded, not raised
    assert _valid_closed_tour(res.tour, 8)
    assert ladder.rung_failures["bnb"] == 1


def test_ladder_timeout_teaches_estimator():
    """Finding-2 regression: a pipeline rung that times out must still
    update the latency EWMA, so the ladder stops promising it."""

    class NeverTicket:
        def wait(self, timeout=None):
            import time as _t

            _t.sleep(min(timeout or 0.01, 0.05))
            return None  # simulated: batch never completes in budget

    class StuckScheduler:
        def submit(self, dists):
            return NeverTicket()

        def close(self):
            pass

    ladder = DeadlineLadder(StuckScheduler(), LadderConfig(bnb_max_n=0))
    rng = np.random.default_rng(22)
    xy = rng.uniform(0, 1000, (8, 2))
    res = ladder.solve(xy, deadline_s=0.6)  # > pipeline prior of 0.5
    assert res.tier == "greedy"
    # the burned budget was observed: estimate rose above the prior
    assert ladder.estimator.estimate("pipeline", 8, 0.0) > 0.0


def test_run_jsonl_streams_responses_before_input_ends():
    """Finding-5 regression: responses must be written as they complete,
    not after the input iterable is exhausted (interactive pipe clients)."""
    import threading as _threading

    rng = np.random.default_rng(23)
    seen = _threading.Event()
    gate = _threading.Event()

    class StreamingOut:
        def __init__(self):
            self.lines = []

        def write(self, s):
            self.lines.append(s)
            seen.set()

        def flush(self):
            pass

    def lazy_lines():
        yield json.dumps(
            {"id": "first", "xy": rng.uniform(0, 1000, (7, 2)).tolist(),
             "deadline_ms": 60_000}
        )
        # block the INPUT until the first response has been written
        assert seen.wait(timeout=60.0), "no response before input ended"
        gate.set()
        yield json.dumps(
            {"id": "second", "xy": rng.uniform(0, 1000, (7, 2)).tolist(),
             "deadline_ms": 60_000}
        )

    out = StreamingOut()
    run_jsonl(lazy_lines(), out, _cfg(threads=2))
    assert gate.is_set()
    rows = [json.loads(x) for x in out.lines]
    assert [r["id"] for r in rows] == ["first", "second"]


def test_serve_cli_reads_and_writes_files(tmp_path):
    from tsp_mpi_reduction_tpu.utils.cli import main

    rng = np.random.default_rng(14)
    inp = tmp_path / "req.jsonl"
    outp = tmp_path / "resp.jsonl"
    reqs = [
        {"id": i, "xy": rng.uniform(0, 1000, (7, 2)).tolist(),
         "deadline_ms": 60_000}
        for i in range(3)
    ]
    inp.write_text("".join(json.dumps(r) + "\n" for r in reqs))
    rc = main([
        "serve", "--in", str(inp), "--out", str(outp),
        "--backend", "cpu", "--max-wait-ms", "5",
    ])
    assert rc == 0
    rows = [json.loads(x) for x in outp.read_text().strip().splitlines()]
    assert [r["id"] for r in rows] == [0, 1, 2]
    for r in rows:
        assert _valid_closed_tour(r["tour"], 7)

"""Resilience layer: fault registry, crash-safe checkpoint store, retry,
scheduler watchdog.

The headline property test kills the checkpoint writer at EVERY byte
offset of the file image (fault-registry truncate mode) and asserts the
store always hands back the previous valid snapshot — the exact failure
the legacy bare ``np.savez_compressed`` could not survive.
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from tsp_mpi_reduction_tpu.resilience import checkpoint as ck
from tsp_mpi_reduction_tpu.resilience import faults
from tsp_mpi_reduction_tpu.resilience.faults import FaultInjected, TransientFault
from tsp_mpi_reduction_tpu.resilience.health import HEALTH
from tsp_mpi_reduction_tpu.resilience.retry import RetryPolicy


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


# -- fault spec grammar --------------------------------------------------------


def test_parse_spec_grammar():
    clauses = faults.parse_spec(
        "ckpt.write:truncate,nth=2,at=100,seed=7;cache.get:raise,count=3"
    )
    assert len(clauses) == 2
    c0, c1 = clauses
    assert (c0.seam, c0.mode, c0.nth, c0.at, c0.seed) == (
        "ckpt.write", "truncate", 2, 100, 7,
    )
    assert (c1.seam, c1.mode, c1.count) == ("cache.get", "raise", 3)


@pytest.mark.parametrize(
    "bad",
    [
        "nosuchseam:raise",            # unregistered seam
        "ckpt.write:explode",          # unknown mode
        "ckpt.write",                  # missing mode
        "ckpt.write:raise,nth=zero",   # unparsable int
        "ckpt.write:raise,nth=0",      # nth < 1
        "ckpt.write:raise,wat=1",      # unknown key
    ],
)
def test_parse_spec_rejects_typos_loudly(bad):
    """A silently-ignored chaos clause would test nothing: every typo is a
    hard error."""
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_env_spec_initializes_registry():
    reg = faults.FaultRegistry("cache.get:raise")
    with pytest.raises(FaultInjected):
        reg.fire("cache.get")


def test_nth_count_window():
    faults.configure("cache.get:raise,nth=2,count=2")
    reg = faults.registry()
    reg.fire("cache.get")  # hit 1: clean
    for _ in range(2):  # hits 2-3: armed
        with pytest.raises(FaultInjected):
            reg.fire("cache.get")
    reg.fire("cache.get")  # hit 4: window closed
    assert reg.hits("cache.get") == 4


def test_count_zero_is_unbounded():
    faults.configure("cache.put:raise,count=0")
    reg = faults.registry()
    for _ in range(5):
        with pytest.raises(FaultInjected):
            reg.fire("cache.put")


def test_unregistered_seam_is_an_error():
    with pytest.raises(ValueError, match="unregistered"):
        faults.registry().fire("not.a.seam")


def test_truncate_is_deterministic_and_at_is_exact():
    blob = bytes(range(200))
    a = faults.FaultRegistry("ckpt.write:truncate,seed=3")
    b = faults.FaultRegistry("ckpt.write:truncate,seed=3")
    cut_a, kind = a.filter_bytes("ckpt.write", blob)
    cut_b, _ = b.filter_bytes("ckpt.write", blob)
    assert kind == "truncate" and cut_a == cut_b and len(cut_a) < len(blob)
    exact = faults.FaultRegistry("ckpt.write:truncate,at=17")
    cut, _ = exact.filter_bytes("ckpt.write", blob)
    assert cut == blob[:17]


def test_corrupt_flips_bytes_but_keeps_length():
    blob = bytes(1000)
    reg = faults.FaultRegistry("ckpt.read:corrupt,seed=1")
    out, kind = reg.filter_bytes("ckpt.read", blob)
    assert kind == "corrupt" and len(out) == len(blob) and out != blob


def test_delay_mode_sleeps_then_passes():
    faults.configure("ladder.rung:delay,delay_ms=30")
    t0 = time.monotonic()
    faults.registry().fire("ladder.rung")  # no raise
    assert time.monotonic() - t0 >= 0.025


def test_injections_count_into_health():
    before = HEALTH.snapshot()["faults_injected"].get("cache.get", 0)
    faults.configure("cache.get:raise")
    with pytest.raises(FaultInjected):
        faults.registry().fire("cache.get")
    assert HEALTH.snapshot()["faults_injected"]["cache.get"] == before + 1


# -- checkpoint store ----------------------------------------------------------


def test_pack_unpack_roundtrip_and_header():
    payload = b"the campaign state"
    blob = ck.pack(payload, fingerprint="abc123")
    header, out = ck.unpack(blob)
    assert out == payload
    assert header["fingerprint"] == "abc123"
    assert header["payload_len"] == len(payload)


def test_unpack_detects_truncation_and_corruption():
    blob = ck.pack(b"x" * 100, fingerprint=None)
    for cut in (3, len(ck.MAGIC) + 2, len(blob) - 1):
        with pytest.raises(ck.CheckpointError):
            ck.unpack(blob[:cut])
    flipped = bytearray(blob)
    flipped[-10] ^= 0xFF
    with pytest.raises(ck.CheckpointError, match="checksum"):
        ck.unpack(bytes(flipped))


def test_unpack_accepts_legacy_bare_npz():
    buf = io.BytesIO()
    np.savez_compressed(buf, a=np.arange(3))
    legacy = buf.getvalue()
    header, payload = ck.unpack(legacy)
    assert header is None and payload == legacy
    z = np.load(io.BytesIO(payload))
    np.testing.assert_array_equal(z["a"], np.arange(3))


def test_write_atomic_rotation_keeps_last_n(tmp_path):
    path = str(tmp_path / "c.npz")
    for i in range(5):
        ck.write_atomic(path, f"snap{i}".encode(), keep=3)
    _, payload, src, fallbacks = ck.read_with_fallback(path, keep=3)
    assert (payload, src, fallbacks) == (b"snap4", path, 0)
    assert ck.unpack(open(path + ".1", "rb").read())[1] == b"snap3"
    assert ck.unpack(open(path + ".2", "rb").read())[1] == b"snap2"
    assert not os.path.exists(path + ".3")  # oldest dropped


def test_read_falls_back_past_corrupt_newest(tmp_path):
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"good-old")
    ck.write_atomic(path, b"good-new")
    with open(path, "r+b") as f:  # bit-rot the newest snapshot in place
        f.seek(-4, os.SEEK_END)
        f.write(b"\xff\xff\xff\xff")
    before = HEALTH.get("fallback_restores")
    header, payload, src, fallbacks = ck.read_with_fallback(path)
    assert payload == b"good-old" and src == path + ".1" and fallbacks == 1
    assert HEALTH.get("fallback_restores") == before + 1


def test_transient_read_fault_is_retried_not_fallen_back(tmp_path):
    """One read hiccup must not cost a rotation step of progress: the
    per-candidate retry absorbs it and the NEWEST snapshot is returned."""
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"older")
    ck.write_atomic(path, b"newer")
    faults.configure("ckpt.read:raise")  # count=1: one transient hiccup
    before = HEALTH.get("retries")
    _, payload, src, fallbacks = ck.read_with_fallback(path)
    assert (payload, src, fallbacks) == (b"newer", path, 0)
    assert HEALTH.get("retries") == before + 1


def test_persistent_read_fault_falls_back(tmp_path):
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"older")
    ck.write_atomic(path, b"newer")
    # count=2 defeats the read retry: the candidate is written off and
    # the store falls back to the previous rotation snapshot
    faults.configure("ckpt.read:raise,count=2")
    _, payload, src, _ = ck.read_with_fallback(path)
    assert payload == b"older" and src == path + ".1"


def test_read_raises_when_no_candidate_survives(tmp_path):
    path = str(tmp_path / "c.npz")
    with pytest.raises(ck.CheckpointError, match="missing"):
        ck.read_with_fallback(path)


def test_writer_killed_at_every_byte_offset_preserves_previous(tmp_path):
    """THE crash-safety property: for EVERY byte offset of the file image,
    a writer killed there (truncate mode publishes the torn image, then
    crashes) leaves the store able to hand back the full previous
    snapshot. This is the failure mode that used to destroy a campaign's
    only checkpoint."""
    v1, v2 = b"snapshot-one!", b"snapshot-two."
    image_len = len(ck.pack(v2, fingerprint="deadbeef"))
    for offset in range(image_len):
        root = tmp_path / f"o{offset}"
        root.mkdir()
        path = str(root / "c.npz")
        ck.write_atomic(path, v1, fingerprint="deadbeef")
        faults.configure(f"ckpt.write:truncate,at={offset}")
        with pytest.raises(FaultInjected):
            ck.write_atomic(path, v2, fingerprint="deadbeef")
        faults.clear()
        header, payload, _src, fallbacks = ck.read_with_fallback(path)
        assert payload == v1, f"offset {offset}: lost the valid snapshot"
        assert fallbacks == 1  # torn newest was detected, not trusted
        assert header["fingerprint"] == "deadbeef"


def test_raise_mode_write_crash_leaves_store_untouched(tmp_path):
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"only")
    faults.configure("ckpt.write:raise")
    with pytest.raises(FaultInjected):
        ck.write_atomic(path, b"never-lands")
    faults.clear()
    assert ck.read_with_fallback(path)[1] == b"only"


def test_read_header_and_fingerprint():
    d1 = np.arange(16, dtype=np.float64).reshape(4, 4)
    d2 = d1.copy()
    d2[0, 1] += 1e-9
    fp1, fp1b, fp2 = (
        ck.instance_fingerprint(d1),
        ck.instance_fingerprint(d1.copy()),
        ck.instance_fingerprint(d2),
    )
    assert fp1 == fp1b and fp1 != fp2  # content hash, byte-exact


def test_read_header_from_file(tmp_path):
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"payload", fingerprint="f00d")
    header = ck.read_header(path)
    assert header["fingerprint"] == "f00d"
    legacy = str(tmp_path / "legacy.npz")
    buf = io.BytesIO()
    np.savez_compressed(buf, a=np.arange(2))
    with open(legacy, "wb") as f:  # graftlint: disable=R6 — fixture setup
        f.write(buf.getvalue())
    assert ck.read_header(legacy) is None


def test_write_json_atomic(tmp_path):
    path = str(tmp_path / "artifact.json")
    ck.write_json_atomic(path, {"ok": True})
    with open(path) as f:
        assert json.load(f) == {"ok": True}
    assert not os.path.exists(path + ".tmp")


# -- retry policy --------------------------------------------------------------


def test_retry_absorbs_transient_faults_and_counts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("transient")
        return "ok"

    before = HEALTH.get("retries")
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001, seed=0)
    assert policy.call(flaky) == "ok"
    assert len(calls) == 3
    assert HEALTH.get("retries") == before + 2


def test_retry_gives_up_after_max_attempts():
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.001, seed=0)
    calls = []

    def always():
        calls.append(1)
        raise TransientFault("still down")

    with pytest.raises(TransientFault):
        policy.call(always)
    assert len(calls) == 2


def test_retry_does_not_touch_non_transient_errors():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5, base_delay_s=0.001, seed=0).call(boom)
    assert len(calls) == 1  # no retry: this is not a transient fault


def test_retry_backoff_is_deterministic_and_bounded():
    import random

    policy = RetryPolicy(
        max_attempts=5, base_delay_s=0.01, max_delay_s=0.04, jitter=0.5, seed=42
    )
    a = [policy.delay_s(i, random.Random(42)) for i in range(1, 5)]
    b = [policy.delay_s(i, random.Random(42)) for i in range(1, 5)]
    assert a == b  # seeded jitter replays byte-identically
    for i, delay in enumerate(a, start=1):
        raw = min(0.01 * 2 ** (i - 1), 0.04)
        assert raw * 0.5 <= delay <= raw


def test_retry_respects_wall_budget():
    t0 = time.monotonic()
    with pytest.raises(TransientFault):
        RetryPolicy(
            max_attempts=100, base_delay_s=0.05, max_delay_s=0.05, jitter=0.0
        ).call(lambda: (_ for _ in ()).throw(TransientFault("x")), budget_s=0.02)
    assert time.monotonic() - t0 < 1.0  # gave up on budget, not attempts


# -- health counters -----------------------------------------------------------


def test_health_snapshot_always_carries_standard_keys():
    snap = HEALTH.snapshot()
    for key in ("worker_restarts", "stuck_restarts", "retries",
                "fallback_restores", "faults_injected"):
        assert key in snap


def test_health_counters_are_thread_safe():
    h = __import__(
        "tsp_mpi_reduction_tpu.resilience.health", fromlist=["HealthCounters"]
    ).HealthCounters()

    def bump():
        for _ in range(1000):
            h.incr("retries")

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.get("retries") == 8000


# -- chunked-driver resume fingerprint pre-flight ------------------------------


def _load_chunked_module():
    import importlib.util
    import pathlib

    tool = pathlib.Path(__file__).resolve().parent.parent / "tools" / "bnb_chunked.py"
    spec = importlib.util.spec_from_file_location("bnb_chunked_under_test", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chunked_resume_refuses_wrong_instance(tmp_path):
    """Satellite: --resume on a checkpoint whose header fingerprint does
    not match the requested instance must be a clear pre-flight error,
    not a silently-resumed wrong search."""
    from tsp_mpi_reduction_tpu.utils import tsplib

    mod = _load_chunked_module()
    path = str(tmp_path / "c.npz")
    wrong_d = tsplib.resolve_instance("ulysses16").distance_matrix()
    ck.write_atomic(path, b"payload", fingerprint=ck.instance_fingerprint(wrong_d))
    err = mod._verify_resume_fingerprint(path, "burma14")
    assert "different instance" in err and "burma14" in err


def test_chunked_resume_accepts_matching_and_legacy(tmp_path):
    from tsp_mpi_reduction_tpu.utils import tsplib

    mod = _load_chunked_module()
    path = str(tmp_path / "c.npz")
    d = tsplib.resolve_instance("burma14").distance_matrix()
    ck.write_atomic(path, b"payload", fingerprint=ck.instance_fingerprint(d))
    assert mod._verify_resume_fingerprint(path, "burma14") == ""
    # legacy headerless checkpoint: pre-flight defers to the in-chunk check
    legacy = str(tmp_path / "legacy.npz")
    buf = io.BytesIO()
    np.savez_compressed(buf, a=np.arange(2))
    with open(legacy, "wb") as f:  # graftlint: disable=R6 — fixture setup
        f.write(buf.getvalue())
    assert mod._verify_resume_fingerprint(legacy, "burma14") == ""
    # corrupt newest: not a mismatch — rotation fallback handles it later
    with open(path, "r+b") as f:
        f.write(b"\x00\x00")
    assert mod._verify_resume_fingerprint(path, "burma14") == ""


def test_chunked_driver_retries_a_crashed_chunk(tmp_path, monkeypatch, capsys):
    """A chunk subprocess that dies (killed writer, lapsed grant) is
    re-run — the crash-safe checkpoint makes the retry resume from the
    newest valid snapshot — instead of aborting the whole campaign."""
    import sys as _sys

    mod = _load_chunked_module()
    calls = []
    line = json.dumps({
        "instance": "burma14", "cost": 3323.0, "proven_optimal": True,
        "lower_bound": 3323.0, "lb_raw": 3323.0, "lb_certified": 3323.0,
    })

    class _Result:
        def __init__(self, rc, out):
            self.returncode, self.stdout, self.stderr = rc, out, ""

    def fake_run(cmd, **kw):
        calls.append(list(cmd))
        if len(calls) == 1:
            return _Result(1, "")  # chunk 1, attempt 1: crashed
        return _Result(0, line + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    monkeypatch.setattr(_sys, "argv", [
        "bnb_chunked", "burma14", "--max-chunks=3",
        f"--checkpoint={tmp_path}/c.npz", "--chunk-retries=1",
    ])
    rc = mod.main()
    out = capsys.readouterr()
    assert rc == 0
    assert len(calls) == 2  # attempt 1 failed, retry answered
    assert "retrying (1/1)" in out.err
    summary = json.loads(out.out.strip().splitlines()[-1])
    assert summary["proven_optimal"] and summary["chunks"] == 1


def test_chunked_driver_gives_up_after_retry_budget(tmp_path, monkeypatch, capsys):
    import sys as _sys

    mod = _load_chunked_module()
    calls = []

    class _Fail:
        returncode, stdout, stderr = 1, "", "boom\n"

    monkeypatch.setattr(
        mod.subprocess, "run", lambda cmd, **kw: (calls.append(1), _Fail())[1]
    )
    monkeypatch.setattr(_sys, "argv", [
        "bnb_chunked", "burma14", "--max-chunks=3",
        f"--checkpoint={tmp_path}/c.npz", "--chunk-retries=2",
    ])
    assert mod.main() == 1
    assert len(calls) == 3  # 1 attempt + 2 retries, then abort


def test_chunked_resume_gate_sees_rotation_snapshots(tmp_path):
    """A crash inside the store's rotation window leaves the primary path
    missing but a valid ``.1`` — the driver must treat that as an
    existing campaign (refuse a fresh run / pass --resume), never as a
    clean slate that silently restarts from scratch."""
    mod = _load_chunked_module()
    path = str(tmp_path / "c.npz")
    ck.write_atomic(path, b"snap1")
    ck.write_atomic(path, b"snap2")
    os.replace(path, path + ".1")  # simulate the mid-rotation crash state
    cands = mod._ckpt_candidates(path)
    assert cands == [path + ".1"]
    # the fingerprint pre-flight also reads the surviving candidate
    from tsp_mpi_reduction_tpu.utils import tsplib

    d = tsplib.resolve_instance("burma14").distance_matrix()
    ck.write_atomic(path + "", b"x", fingerprint=ck.instance_fingerprint(d))
    os.replace(path, path + ".1")
    assert mod._verify_resume_fingerprint(path, "burma14") == ""
    assert "different instance" in mod._verify_resume_fingerprint(path, "ulysses16")


def test_fire_fast_path_skips_counting_without_clauses():
    """Production runs (no TSP_FAULTS) must not pay the registry lock per
    seam crossing; hit counters only accumulate under an active spec."""
    reg = faults.FaultRegistry(None)
    reg.fire("cache.get")
    assert reg.hits("cache.get") == 0  # fast path: untracked
    with pytest.raises(ValueError):  # seam names still validated
        reg.fire("not.a.seam")
    blob, kind = reg.filter_bytes("ckpt.write", b"abc")
    assert (blob, kind) == (b"abc", None)
    reg.configure("cache.get:raise,nth=2")
    reg.fire("cache.get")
    assert reg.hits("cache.get") == 1  # counting resumes with clauses


def test_chunked_driver_retry_respects_campaign_wall_budget(
    tmp_path, monkeypatch, capsys
):
    """A hung chunk must not be retried past --time-limit: the attempt
    loop bails (and caps the subprocess timeout) on the remaining budget
    instead of burning chunk_retries x chunk_timeout of grant time."""
    import sys as _sys

    mod = _load_chunked_module()
    calls = []
    monkeypatch.setattr(mod.subprocess, "run", lambda cmd, **kw: calls.append(1))
    monkeypatch.setattr(_sys, "argv", [
        "bnb_chunked", "burma14", "--max-chunks=3", "--chunk-retries=5",
        f"--checkpoint={tmp_path}/c.npz", "--time-limit=0.000001",
    ])
    assert mod.main() == 1
    err = capsys.readouterr().err
    assert "wall budget exhausted" in err
    assert calls == []  # no attempt launched past the budget

"""Mesh-sharded pipeline on the 8-virtual-device CPU mesh.

The JAX analog of multi-rank MPI testing without a cluster (SURVEY.md §4):
`--xla_force_host_platform_device_count=8` in conftest gives 8 real XLA
devices, so shard_map + ppermute execute the actual collective code paths.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models.distributed import run_pipeline_sharded
from tsp_mpi_reduction_tpu.models.pipeline import run_pipeline
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.generator import generate_instance
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, make_padded, merge_tours
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh, make_torus_mesh, torus_dims
from tsp_mpi_reduction_tpu.parallel.reduce import (
    assign_blocks_to_ranks,
    rank_block_counts,
    tree_schedule,
)


def test_rank_block_counts_reference_semantics():
    # direct emulation of tsp.cpp:167-171
    for nb, p in [(6, 3), (10, 4), (7, 8), (20, 6), (10, 20)]:
        expected = [0] * p
        left = nb
        while left:
            expected[left % p] += 1
            left -= 1
        assert rank_block_counts(nb, p) == expected


def test_tree_schedule_shapes():
    assert tree_schedule(1) == []
    assert tree_schedule(2) == [("tree_d0", [(1, 0)])]
    sched = dict(tree_schedule(6))
    assert sched["downshift"] == [(4, 0), (5, 1)]
    assert sched["tree_d0"] == [(1, 0), (3, 2)]
    assert sched["tree_d1"] == [(2, 0)]


def test_torus_dims():
    assert torus_dims(4) == (2, 2)
    assert torus_dims(8) == (2, 4)
    assert torus_dims(7) == (7, 1)


def test_single_rank_matches_oracle(goldens_dir):
    g = json.loads((goldens_dir / "full_10x6_500x500.json").read_text())
    mesh = make_rank_mesh(1)
    res = run_pipeline_sharded(10, 6, 500, 500, mesh=mesh)
    assert res.cost == g["final"]["cost"]
    np.testing.assert_array_equal(res.tour_ids, g["final"]["ids"])


def host_tree_emulation(n, nb, gx, gy, p):
    """Same tree, same operator, sequentially on one device — the control."""
    _, xy = generate_instance(n, nb, gx, gy)
    dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
    costs, local_tours = solve_blocks_from_dists(distance_matrix_np(xy))
    tours = np.asarray(local_tours) + (np.arange(nb)[:, None] * n)
    cap = nb * n + 1
    sols = {}
    for r, blocks in enumerate(assign_blocks_to_ranks(nb, p)):
        acc = None
        for b in blocks:
            t = make_padded(tours[b], n + 1, jnp.asarray(costs[b]), cap)
            acc = t if acc is None else merge_tours(acc, t, dist)
        sols[r] = acc
    for _name, pairs in tree_schedule(p):
        for src, dst in pairs:
            if sols.get(src) is None:
                continue
            if sols.get(dst) is None:
                sols[dst] = sols[src]
            else:
                sols[dst] = merge_tours(sols[dst], sols[src], dist)
            sols[src] = None
    final = sols[0]
    return float(final.cost), np.asarray(final.ids)[: int(final.length)]


@pytest.mark.parametrize("p", [2, 4, 6, 8])
def test_sharded_matches_host_emulation(p):
    n, nb = 5, 12
    mesh = make_rank_mesh(p)
    res = run_pipeline_sharded(n, nb, 1000, 1000, mesh=mesh)
    want_cost, want_ids = host_tree_emulation(n, nb, 1000, 1000, p)
    assert res.cost == want_cost
    np.testing.assert_array_equal(res.tour_ids, want_ids)
    # structural invariants
    assert res.tour_ids[0] == res.tour_ids[-1]
    assert sorted(res.tour_ids[:-1]) == list(range(n * nb))


def test_idle_ranks():
    # more ranks than blocks: reference UB territory (SURVEY.md §5); here
    # idle ranks carry zero-length solutions and the tree still reduces
    mesh = make_rank_mesh(8)
    res = run_pipeline_sharded(4, 5, 500, 500, mesh=mesh)
    assert sorted(res.tour_ids[:-1]) == list(range(20))


def test_torus_mesh_runs():
    mesh = make_torus_mesh(jax.devices()[:4])
    assert mesh.devices.shape == (2, 2)


def test_initialize_multihost_single_process():
    """initialize_multihost joins a (1-process) jax.distributed cluster.

    Run in a subprocess: jax.distributed must initialize before any backend,
    and this test process already has one.
    """
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # ephemeral free port; no cross-run collision
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax._src import xla_bridge as xb\n"
        "xb._backend_factories.pop('axon', None)\n"
        "from tsp_mpi_reduction_tpu.parallel.mesh import initialize_multihost\n"
        f"n = initialize_multihost('localhost:{port}', 1, 0)\n"
        "assert n >= 1, n\n"
        f"n2 = initialize_multihost('localhost:{port}', 1, 0)  # idempotent\n"
        "assert n2 == n\n"
        "print('multihost-ok', n)\n"
    )
    import pathlib

    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent),
    )
    assert "multihost-ok" in out.stdout, (out.stdout, out.stderr)


# --- --compat-bugs: quirk #5 byte-parity emulation ---


def _ref_merge_blocks(t1, c1, t2, c2, d):
    """Literal host simulation of the reference's mergeBlocks semantics
    (tsp.cpp:197-269), built on Python lists + rotation exactly as the
    C++ operates on vectors — an implementation path independent of
    ops.merge. Closed tours in, closed tour out; formulaic cost."""
    cities1, cities2 = list(t1), list(t2)
    n1, n2 = len(cities1), len(cities2)
    best = None
    # double rotate scan: i-major over tour1 positions, j-minor over tour2
    for i in range(n1):
        a = cities1[i]
        b = cities1[(i + 1) % n1]
        for j in range(n2):
            r1 = cities2[j]
            r2 = cities2[(j + 1) % n2]
            sc = ((d[a, r2] + d[b, r1]) - d[a, b]) - d[r1, r2]
            if best is None or sc < best[0]:
                best = (sc, a, b, r1, r2)
    sc, a, b, r1, r2 = best
    work2 = cities2[:-1]  # pop the closing duplicate
    # rotate until the head VALUE equals the chosen right-edge HEAD
    # (bestSwapEdges.second.first, tsp.cpp:236-239), then ONE more rotation
    # (tsp.cpp:242); a missing value would hang the real reference
    if r1 not in work2:
        raise RuntimeError("reference would hang here (quirk #6 mechanism)")
    while work2[0] != r1:
        work2 = work2[1:] + work2[:1]
    work2 = work2[1:] + work2[:1]
    out = []
    placed = False
    for c in cities1:
        out.append(c)
        if not placed and (c == a or c == b):
            out.extend(reversed(work2))
            placed = True
    return out, (c1 + c2) + sc


def _ref_buggy_reduce(rank_tours, rank_costs, d):
    """Literal simulation of MPI_ManualReduce incl. the never-cleared
    receive vector (tsp.cpp:67,93-95,114-117)."""
    p = len(rank_tours)
    sol = [list(t) for t in rank_tours]
    cost = list(rank_costs)
    accum = [[] for _ in range(p)]
    for _name, pairs in tree_schedule(p):
        for s, r in pairs:
            accum[r] = accum[r] + sol[s]
            sol[r], cost[r] = _ref_merge_blocks(
                sol[r], cost[r], accum[r], cost[s], d
            )
    return sol[0], cost[0]


@pytest.mark.parametrize("p", [4, 8])
def test_compat_bugs_matches_literal_reference_simulation(p):
    """compat_bugs=True must reproduce, value-for-value, a literal host
    simulation of the reference's corrupted reduce (quirk #5) — the
    closest available stand-in for a real p-rank MPI golden (no MPI
    toolchain exists in this environment)."""
    from tsp_mpi_reduction_tpu.parallel.reduce import (
        compat_capacity,
        tree_reduce_single_device,
    )

    n, nb = 4, 8
    _, xy = generate_instance(n, nb, 300, 300)
    d = distance_matrix_np(xy.reshape(-1, 2))
    costs, local = solve_blocks_from_dists(distance_matrix_np(xy))
    gtours = np.asarray(local) + (np.arange(nb)[:, None] * n)
    costs = np.asarray(costs)

    # per-rank sequential folds (reference local fold; clean — the bug is
    # only in the reduce). Build via the literal merge too.
    rank_blocks = assign_blocks_to_ranks(nb, p)
    rank_tours, rank_costs = [], []
    for blocks in rank_blocks:
        if not blocks:
            rank_tours.append([])
            rank_costs.append(0.0)
            continue
        t, c = list(gtours[blocks[0]]), float(costs[blocks[0]])
        for bidx in blocks[1:]:
            t, c = _ref_merge_blocks(t, c, list(gtours[bidx]), float(costs[bidx]), d)
        rank_tours.append(t)
        rank_costs.append(c)
    want_tour, want_cost = _ref_buggy_reduce(rank_tours, rank_costs, d)

    # device emulation: blocks laid out per rank with padding slots
    counts = rank_block_counts(nb, p)
    k = max(counts) if max(counts) else 1
    slot_tours = np.zeros((p * k, n + 1), np.int32)
    slot_costs = np.zeros(p * k, np.float64)
    slot_valid = np.zeros(p * k, bool)
    for r, blocks in enumerate(rank_blocks):
        for i, bidx in enumerate(blocks):
            slot_tours[r * k + i] = gtours[bidx]
            slot_costs[r * k + i] = costs[bidx]
            slot_valid[r * k + i] = True
    cap = compat_capacity(nb, n, p)
    ids, length, cost = tree_reduce_single_device(
        jnp.asarray(slot_tours),
        jnp.asarray(slot_costs),
        jnp.asarray(slot_valid),
        jnp.asarray(d),
        cap,
        p,
        compat_bugs=True,
    )
    assert float(cost) == pytest.approx(want_cost, rel=1e-12)
    assert np.asarray(ids)[: int(length)].tolist() == want_tour


def test_merge_parity_on_corrupted_operands_fuzz():
    """Bit parity of merge_tours vs the literal reference simulation on
    CORRUPTED (duplicate-id, concatenated) second operands — the regime
    --compat-bugs exercises, including argmins landing on the wrap edge."""
    rng = np.random.default_rng(0)
    checked = 0
    for seed in range(40):
        n_ids = 7
        d = np.rint(
            distance_matrix_np(rng.uniform(0, 50, (n_ids, 2)))
        )
        l1 = int(rng.integers(3, 6))
        t1_open = rng.permutation(n_ids)[:l1]
        t1 = np.concatenate([t1_open, t1_open[:1]])
        # corrupted operand: concatenation of two closed sub-tours
        a = rng.permutation(n_ids)[: int(rng.integers(3, 5))]
        b = rng.permutation(n_ids)[: int(rng.integers(3, 5))]
        t2 = np.concatenate([a, a[:1], b, b[:1]])
        try:
            want_tour, want_cost = _ref_merge_blocks(
                list(t1), 10.0, list(t2), 20.0, d
            )
        except RuntimeError:
            continue  # real reference would hang on this operand
        cap = len(t1) + len(t2) + 4
        m = merge_tours(
            make_padded(t1, len(t1), 10.0, cap),
            make_padded(t2, len(t2), 20.0, cap),
            jnp.asarray(d),
        )
        got = np.asarray(m.ids)[: int(m.length)].tolist()
        assert got == want_tour, f"seed {seed}: {got} != {want_tour}"
        assert float(m.cost) == pytest.approx(want_cost, rel=1e-12)
        checked += 1
    assert checked >= 20  # the fuzz actually exercised real cases

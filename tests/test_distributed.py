"""Mesh-sharded pipeline on the 8-virtual-device CPU mesh.

The JAX analog of multi-rank MPI testing without a cluster (SURVEY.md §4):
`--xla_force_host_platform_device_count=8` in conftest gives 8 real XLA
devices, so shard_map + ppermute execute the actual collective code paths.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsp_mpi_reduction_tpu.models.distributed import run_pipeline_sharded
from tsp_mpi_reduction_tpu.models.pipeline import run_pipeline
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.generator import generate_instance
from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, make_padded, merge_tours
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh, make_torus_mesh, torus_dims
from tsp_mpi_reduction_tpu.parallel.reduce import (
    assign_blocks_to_ranks,
    rank_block_counts,
    tree_schedule,
)


def test_rank_block_counts_reference_semantics():
    # direct emulation of tsp.cpp:167-171
    for nb, p in [(6, 3), (10, 4), (7, 8), (20, 6), (10, 20)]:
        expected = [0] * p
        left = nb
        while left:
            expected[left % p] += 1
            left -= 1
        assert rank_block_counts(nb, p) == expected


def test_tree_schedule_shapes():
    assert tree_schedule(1) == []
    assert tree_schedule(2) == [("tree_d0", [(1, 0)])]
    sched = dict(tree_schedule(6))
    assert sched["downshift"] == [(4, 0), (5, 1)]
    assert sched["tree_d0"] == [(1, 0), (3, 2)]
    assert sched["tree_d1"] == [(2, 0)]


def test_torus_dims():
    assert torus_dims(4) == (2, 2)
    assert torus_dims(8) == (2, 4)
    assert torus_dims(7) == (7, 1)


def test_single_rank_matches_oracle(goldens_dir):
    g = json.loads((goldens_dir / "full_10x6_500x500.json").read_text())
    mesh = make_rank_mesh(1)
    res = run_pipeline_sharded(10, 6, 500, 500, mesh=mesh)
    assert res.cost == g["final"]["cost"]
    np.testing.assert_array_equal(res.tour_ids, g["final"]["ids"])


def host_tree_emulation(n, nb, gx, gy, p):
    """Same tree, same operator, sequentially on one device — the control."""
    _, xy = generate_instance(n, nb, gx, gy)
    dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
    costs, local_tours = solve_blocks_from_dists(distance_matrix_np(xy))
    tours = np.asarray(local_tours) + (np.arange(nb)[:, None] * n)
    cap = nb * n + 1
    sols = {}
    for r, blocks in enumerate(assign_blocks_to_ranks(nb, p)):
        acc = None
        for b in blocks:
            t = make_padded(tours[b], n + 1, jnp.asarray(costs[b]), cap)
            acc = t if acc is None else merge_tours(acc, t, dist)
        sols[r] = acc
    for _name, pairs in tree_schedule(p):
        for src, dst in pairs:
            if sols.get(src) is None:
                continue
            if sols.get(dst) is None:
                sols[dst] = sols[src]
            else:
                sols[dst] = merge_tours(sols[dst], sols[src], dist)
            sols[src] = None
    final = sols[0]
    return float(final.cost), np.asarray(final.ids)[: int(final.length)]


@pytest.mark.parametrize("p", [2, 4, 6, 8])
def test_sharded_matches_host_emulation(p):
    n, nb = 5, 12
    mesh = make_rank_mesh(p)
    res = run_pipeline_sharded(n, nb, 1000, 1000, mesh=mesh)
    want_cost, want_ids = host_tree_emulation(n, nb, 1000, 1000, p)
    assert res.cost == want_cost
    np.testing.assert_array_equal(res.tour_ids, want_ids)
    # structural invariants
    assert res.tour_ids[0] == res.tour_ids[-1]
    assert sorted(res.tour_ids[:-1]) == list(range(n * nb))


def test_idle_ranks():
    # more ranks than blocks: reference UB territory (SURVEY.md §5); here
    # idle ranks carry zero-length solutions and the tree still reduces
    mesh = make_rank_mesh(8)
    res = run_pipeline_sharded(4, 5, 500, 500, mesh=mesh)
    assert sorted(res.tour_ids[:-1]) == list(range(20))


def test_torus_mesh_runs():
    mesh = make_torus_mesh(jax.devices()[:4])
    assert mesh.devices.shape == (2, 2)


def test_initialize_multihost_single_process():
    """initialize_multihost joins a (1-process) jax.distributed cluster.

    Run in a subprocess: jax.distributed must initialize before any backend,
    and this test process already has one.
    """
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # ephemeral free port; no cross-run collision
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax._src import xla_bridge as xb\n"
        "xb._backend_factories.pop('axon', None)\n"
        "from tsp_mpi_reduction_tpu.parallel.mesh import initialize_multihost\n"
        f"n = initialize_multihost('localhost:{port}', 1, 0)\n"
        "assert n >= 1, n\n"
        f"n2 = initialize_multihost('localhost:{port}', 1, 0)  # idempotent\n"
        "assert n2 == n\n"
        "print('multihost-ok', n)\n"
    )
    import pathlib

    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent),
    )
    assert "multihost-ok" in out.stdout, (out.stdout, out.stderr)

"""Native C++ runtime: parity vs goldens, the Python generator, and JAX.

The native layer must agree bit-for-bit with (a) the committed oracle
goldens, (b) the Python/numpy generator twin, and (c) the JAX float64
pipeline — the three-way check that pins all implementations to the same
contract.
"""

import json

import numpy as np
import pytest

from tsp_mpi_reduction_tpu import native
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.ops.generator import generate_instance, get_blocks_per_dim
from tsp_mpi_reduction_tpu.ops.rand import GlibcRand


@pytest.fixture(scope="module", autouse=True)
def _built():
    native.build()


def test_rand_stream_matches_golden(goldens_dir):
    golden = json.loads((goldens_dir / "glibc_rand_seed0.json").read_text())
    got = native.rand_stream(0, len(golden["values"]))
    assert got.tolist() == golden["values"]


def test_rand_stream_matches_python_nonzero_seeds():
    for seed in (1, 42, 123456789, 2**31 + 7):
        rng = GlibcRand(seed)
        assert native.rand_stream(seed, 500).tolist() == rng.fill(500).tolist()


def test_blocks_per_dim_matches_python():
    for nb in list(range(1, 60)) + [97, 100, 144, 200]:
        assert native.blocks_per_dim(nb) == get_blocks_per_dim(nb)


@pytest.mark.parametrize("config", ["10x6_500x500", "13x4_1000x1000"])
def test_generate_matches_golden(goldens_dir, config):
    golden = json.loads((goldens_dir / f"full_{config}.json").read_text())
    c = golden["config"]
    xy = native.generate(c["ncpb"], c["nblocks"], c["gx"], c["gy"], seed=0)
    gold = np.asarray(
        [[[city[1], city[2]] for city in block] for block in golden["blocks"]]
    )
    np.testing.assert_array_equal(xy, gold)  # bit-exact


def test_generate_matches_python_generator():
    _, xy_py = generate_instance(7, 12, 777, 333, seed=5)
    xy_c = native.generate(7, 12, 777, 333, seed=5)
    np.testing.assert_array_equal(xy_c, xy_py)


@pytest.mark.parametrize("config", ["10x6_500x500", "13x4_1000x1000"])
def test_solve_block_matches_golden(goldens_dir, config):
    golden = json.loads((goldens_dir / f"full_{config}.json").read_text())
    c = golden["config"]
    xy = native.generate(c["ncpb"], c["nblocks"], c["gx"], c["gy"], seed=0)
    for b, sol in enumerate(golden["block_solutions"]):
        dist = distance_matrix_np(xy[b])
        cost, tour = native.solve_block(dist)
        assert cost == sol["cost"]  # bit-exact double
        got_global = (tour + b * c["ncpb"]).tolist()
        assert got_global == sol["ids"]


@pytest.mark.parametrize(
    "config", ["10x6_500x500", "10x10_123x457", "13x4_1000x1000"]
)
def test_pipeline_matches_golden(goldens_dir, config):
    golden = json.loads((goldens_dir / f"full_{config}.json").read_text())
    c = golden["config"]
    cost, tour, block_costs = native.run_pipeline(
        c["ncpb"], c["nblocks"], c["gx"], c["gy"], seed=0, ranks=1
    )
    assert cost == golden["final"]["cost"]
    assert tour.tolist() == golden["final"]["ids"]
    assert block_costs.tolist() == [s["cost"] for s in golden["block_solutions"]]


def test_pipeline_multirank_matches_jax_emulation():
    from tsp_mpi_reduction_tpu.models.distributed import run_pipeline_ranks

    for ranks in (1, 2, 3, 4, 6):
        c_cost, c_tour, _ = native.run_pipeline(6, 12, 800, 600, ranks=ranks)
        j = run_pipeline_ranks(6, 12, 800, 600, ranks, dtype="float64")
        assert c_cost == j.cost
        assert c_tour.tolist() == j.tour_ids.tolist()


def test_merge_matches_jax_operator():
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.ops.merge import PaddedTour, merge_tours

    xy = native.generate(5, 4, 300, 300)
    flat = xy.reshape(-1, 2)
    dist = distance_matrix_np(flat)
    c1, t1 = native.solve_block(distance_matrix_np(xy[0]))
    c2, t2 = native.solve_block(distance_matrix_np(xy[1]))
    t2g = t2 + 5
    n_cost, n_ids = native.merge_tours(flat, t1, c1, t2g, c2)

    cap = len(t1) + len(t2g) - 1
    p1 = PaddedTour(
        jnp.asarray(np.pad(t1, (0, cap - len(t1))), jnp.int32),
        jnp.asarray(len(t1), jnp.int32),
        jnp.asarray(c1),
    )
    p2 = PaddedTour(
        jnp.asarray(t2g, jnp.int32), jnp.asarray(len(t2g), jnp.int32), jnp.asarray(c2)
    )
    merged = merge_tours(p1, p2, jnp.asarray(dist))
    assert float(merged.cost) == n_cost
    assert np.asarray(merged.ids)[: int(merged.length)].tolist() == n_ids.tolist()


def test_native_cli_binary_reference_contract(tmp_path):
    """The standalone tsp-native binary honors the reference's argv/stdout
    contract and is bit-exact with the oracle cost."""
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).resolve().parent.parent
    subprocess.run(
        ["make", "-C", str(root / "native"), "tsp-native"],
        check=True,
        capture_output=True,
    )
    binary = str(root / "native" / "tsp-native")

    r = subprocess.run(
        [binary, "10", "6", "500", "500"], capture_output=True, text=True
    )
    assert r.returncode == 0
    lines = r.stdout.strip().split("\n")
    assert lines[0] == "We have 10 cities for each of our 6 blocks"
    assert lines[1] == "2 blocks in X 3 in Y"
    assert lines[-1].endswith("the trip cost 3720.557435")

    r = subprocess.run([binary, "17", "1", "10", "10"], capture_output=True)
    assert r.returncode == 57  # exit(1337) & 0xFF, like the reference

    r = subprocess.run([binary], capture_output=True, text=True)
    assert r.returncode == 1
    # byte-identical to the reference's usage line (tsp.cpp:282)
    assert r.stdout == "Usage:  ./tsp numCitiesPerBlock numBlocks gridDimX gridDimY\n"

    r = subprocess.run([binary, "17", "1", "10", "10"], capture_output=True, text=True)
    # byte-identical reference scold (tsp.cpp:292)
    assert r.stdout == (
        "Come on... We don't want to wait forever so lets just have you "
        "retry that with less than 16 cities per block...\n"
    )

    r = subprocess.run([binary, "2", "4", "10", "10"], capture_output=True)
    assert r.returncode == 2  # clean error instead of the reference hang

"""Rank-resolved observability (ISSUE 10): RankSampler, starvation
sentinel, imbalance accounting, sharded-solver integration.

Covers the sampler's window cadence + cumulative-to-delta bookkeeping,
the once-per-episode ``rank_starvation`` contract (fires on entry after
``patience`` windows, re-arms only on recovery), the ``rank_balance``
block's math, the per-rank gauge export (rank labels from
``range(num_ranks)`` — the R13-bounded set), the golden schema of the
driver payload's ``rank_series`` / ``obs.rank_balance`` for a sharded
run, the skewed-instance acceptance (starved rank NAMED), coherence of
the per-rank accounting through injected ``spill.fetch`` faults, and
``tools/obs_report.py --ranks`` (render + exit 2 on a payload without
per-rank telemetry).
"""

from __future__ import annotations

import importlib.util
import json
import pathlib

import numpy as np
import pytest

from tsp_mpi_reduction_tpu import obs
from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.obs import anomaly, metrics, rankview, tracing
from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
from tsp_mpi_reduction_tpu.resilience import faults
from tsp_mpi_reduction_tpu.resilience.health import HEALTH

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _obs_clean(monkeypatch):
    """Tracing unconfigured, obs override cleared, and a tight sampling
    window (the integration solves run few dispatches)."""
    monkeypatch.setenv(rankview.ENV_WINDOW, "2")
    tracing.configure(None)
    obs.set_enabled(None)
    yield
    tracing.configure(None)
    obs.set_enabled(None)


def _skewed_d(n=11, seed=33):
    rng = np.random.default_rng(seed)
    return np.rint(distance_matrix_np(rng.uniform(0, 100, (n, 2))) * 10)


#: the measured stranded-rank configuration (VERDICT r4): every root
#: child seeded on rank 0, ring balance with a tiny transfer slab so
#: diffusion to the far ranks is slow — starvation MUST fire; the
#: capacity is tight enough that ranks 0-1 spill (per-rank byte
#: attribution exercised) while the proof still completes in ~2 s
SKEW_KW = dict(
    capacity_per_rank=128, k=4, inner_steps=2, bound="min-out",
    mst_prune=False, node_ascent=0, device_loop=False,
    seed_mode="single-rank", balance="ring", transfer=4,
    max_iters=2_000_000,
)


# -- RankSampler unit ----------------------------------------------------------


def test_rank_sampler_window_cadence_and_deltas():
    s = rankview.RankSampler(num_ranks=2, capacity=8, window=4)
    due = [s.due() for _ in range(9)]
    # first dispatch samples (short runs get >= 1 row), then every 4th
    assert due[0] is True
    s.sample(1, (5, 0), (5, 0), (10, 0), (0, 0), (1, 0), (64, 0), (32, 0),
             (7.0, float("inf")))
    assert not s.pending()
    assert s.due() is False and s.due() is False and s.due() is False
    assert s.due() is True  # window of 4 dispatches complete
    s.sample(5, (3, 2), (3, 1), (16, 4), (0, 1), (1, 2), (64, 96), (32, 40),
             (8.0, 9.0))
    out = s.series()
    assert out["columns"] == list(rankview.RANK_COLUMNS)
    assert out["ranks"] == 2 and out["window"] == 4
    r0, r1 = out["rows"]
    # cumulative inputs became per-window deltas
    assert r0[out["columns"].index("nodes")] == [10, 0]
    assert r1[out["columns"].index("nodes")] == [6, 4]
    assert r1[out["columns"].index("spill_events")] == [0, 2]
    assert r1[out["columns"].index("spill_to_host")] == [0, 96]
    assert r1[out["columns"].index("spill_to_device")] == [0, 40]
    # +inf best bound (drained rank) encodes as null
    assert r0[out["columns"].index("best_bound")] == [7.0, None]
    json.dumps(out)  # strict-JSON encodable


def test_rank_sampler_ring_keeps_newest():
    s = rankview.RankSampler(num_ranks=1, capacity=3, window=1)
    for i in range(7):
        s.due()
        s.sample(i, (1,), (1,), (i,), (0,), (0,), (0,), (0,), (1.0,))
    out = s.series()
    assert out["samples_total"] == 7 and out["samples_dropped"] == 4
    assert [r[0] for r in out["rows"]] == [4, 5, 6]  # oldest-first tail


def test_rank_sampler_maybe_respects_tsp_obs_off():
    obs.set_enabled(False)
    assert rankview.RankSampler.maybe(4) is None
    obs.set_enabled(True)
    s = rankview.RankSampler.maybe(4)
    assert s is not None and s.watch is not None
    assert s.window == 2  # the fixture's ENV_WINDOW


# -- starvation sentinel unit --------------------------------------------------


def test_rank_starvation_fires_once_per_episode_and_rearms():
    sen = anomaly.RankStarvationSentinel(4, starve_ratio=0.1, patience=2)
    starved = ((10, 10, 10, 0), (40, 40, 40, 0))  # rank 3 at zero share
    fed = ((10, 10, 10, 10), (30, 30, 30, 30))
    fired = []
    for step, (occ, nodes) in enumerate([
        starved,   # streak 1: below patience, no fire
        starved,   # streak 2: FIRES
        starved,   # still starved: armed, no re-fire
        fed,       # recovery: episode over, re-arms
        starved,   # streak 1 again
        starved,   # second episode FIRES
    ]):
        fired.extend(sen.observe_window(step, occ, nodes))
    assert [e["step"] for e in fired] == [1, 5]
    assert all(e["kind"] == "rank_starvation" and e["rank"] == 3
               for e in fired)
    assert sen.episodes_per_rank == [0, 0, 0, 2]
    assert len(sen.events) == 2  # exactly once per episode


def test_rank_starvation_quiet_on_drained_mesh_and_single_rank():
    sen = anomaly.RankStarvationSentinel(4, patience=1)
    # zero nodes everywhere = proof endgame, not starvation
    assert sen.observe_window(1, (0, 0, 0, 0), (0, 0, 0, 0)) == []
    solo = anomaly.RankStarvationSentinel(1, patience=1)
    assert solo.observe_window(1, (5,), (100,)) == []
    assert sen.events == [] and solo.events == []


def test_rank_starvation_reaches_health_registry_and_summary():
    reg = metrics.REGISTRY
    before = reg.value("bnb_anomalies_total", kind="rank_starvation")
    h0 = HEALTH.snapshot().get("anomaly_rank_starvation", 0)
    sen = anomaly.RankStarvationSentinel(2, patience=1)
    sen.observe_window(3, (9, 0), (50, 0))
    assert reg.value("bnb_anomalies_total", kind="rank_starvation") == before + 1
    assert HEALTH.snapshot()["anomaly_rank_starvation"] == h0 + 1
    assert sen.summary() == {"events": sen.events, "fired": 1}


def test_merge_summaries_orders_by_step_and_handles_none():
    assert anomaly.merge_summaries(None, None) is None
    stall = anomaly.StallSentinel(window=2)
    rank = anomaly.RankStarvationSentinel(2, patience=1)
    rank.observe_window(7, (5, 0), (40, 0))
    stall.events.append({"kind": "lb_stagnation", "step": 3})
    merged = anomaly.merge_summaries(stall, rank, None)
    assert merged["fired"] == 2
    assert [e["step"] for e in merged["events"]] == [3, 7]


# -- rank_balance / gauge export -----------------------------------------------


def test_rank_balance_math_and_straggler():
    series = {
        "columns": list(rankview.RANK_COLUMNS),
        "rows": [
            [0, [8, 2], [8, 2], [9, 1], [0, 0], [0, 0], [0, 0], [0, 0],
             [1.0, 2.0]],
            [2, [4, 2], [4, 2], [6, 2], [0, 0], [0, 0], [0, 0], [0, 0],
             [1.0, 2.0]],
        ],
        "ranks": 2,
    }
    events = [{"kind": "rank_starvation", "rank": 1, "step": 2},
              {"kind": "lb_stagnation", "step": 4}]
    bal = rankview.rank_balance(
        series, [15, 3], spill_events=[2, 0],
        spill_bytes_to_host=[128, 0], spill_bytes_to_device=[64, 0],
        reservoir=[1, 0], events=events,
    )
    assert bal["ranks"] == 2 and bal["nodes_total"] == 18
    assert bal["straggler_rank"] == 0
    assert bal["straggler_score"] == pytest.approx(15 / 9, abs=1e-3)
    assert bal["nodes_max_min_ratio"] == pytest.approx(5.0)
    assert bal["occupancy_mean"] == [6.0, 2.0]
    assert bal["starved_ranks"] == [1] and bal["starvation_episodes"] == 1
    assert bal["spill_bytes_to_host_per_rank"] == [128, 0]
    json.dumps(bal)


def test_rank_balance_zero_work_is_balanced_not_nan():
    bal = rankview.rank_balance(None, [0, 0, 0])
    assert bal["nodes_cv"] == 0.0 and bal["occupancy_cv"] == 0.0
    assert bal["straggler_score"] == 0.0
    json.dumps(bal)


def test_fold_rank_view_exports_bounded_rank_gauges():
    reg = metrics.REGISTRY
    n0 = reg.value("bnb_rank_nodes_total", rank=1)
    rankview.fold_rank_view({
        "ranks": 2,
        "nodes_per_rank": [10, 4],
        "occupancy_mean": [3.5, 1.5],
        "occupancy_cv": 0.4,
        "nodes_cv": 0.3,
        "straggler_score": 1.4,
        "spill_events_per_rank": [2, 0],
        "spill_bytes_to_host_per_rank": [256, 0],
        "spill_bytes_to_device_per_rank": [128, 0],
    })
    assert reg.value("bnb_rank_nodes_total", rank=1) == n0 + 4
    assert reg.value("bnb_rank_occupancy_mean", rank=0) == 3.5
    assert reg.value("bnb_rank_spill_bytes_total",
                     rank=0, direction="to_host") >= 256
    assert reg.value("bnb_rank_straggler_score") == 1.4


# -- sharded-solver integration ------------------------------------------------


def _solve_skewed(n=11, **over):
    kw = dict(SKEW_KW)
    kw.update(over)
    return bb.solve_sharded(_skewed_d(n), make_rank_mesh(4), **kw)


def test_sharded_rank_series_schema_and_coherence():
    res = _solve_skewed()
    assert res.proven_optimal
    rs, bal = res.rank_series, res.rank_balance
    assert rs is not None and bal is not None
    assert rs["columns"] == list(rankview.RANK_COLUMNS)
    assert rs["ranks"] == 4 and rs["rows"]
    cols = rs["columns"]
    for row in rs["rows"]:
        assert len(row) == len(cols)
        for c in cols[1:]:
            assert len(row[cols.index(c)]) == 4  # one entry per rank
    # per-rank sums reconcile with the aggregate counters
    assert sum(bal["nodes_per_rank"]) == res.nodes_expanded
    assert bal["nodes_per_rank"] == [int(x) for x in res.nodes_per_rank]
    assert sum(bal["spill_bytes_to_host_per_rank"]) == res.spill_bytes_to_host
    assert (
        sum(bal["spill_bytes_to_device_per_rank"])
        == res.spill_bytes_to_device
    )
    assert sum(bal["spill_events_per_rank"]) == res.spill_events
    # the series' window deltas sum to the totals too (no tail lost:
    # the solver flushes a final pending sample at loop exit)
    i_nodes = cols.index("nodes")
    assert (
        sum(sum(r[i_nodes]) for r in rs["rows"]) == res.nodes_expanded
        or rs["samples_dropped"] > 0
    )
    json.dumps(rs), json.dumps(bal)


def test_skewed_run_names_the_starved_rank():
    res = _solve_skewed()
    bal = res.rank_balance
    starve = [e for e in res.anomalies["events"]
              if e["kind"] == "rank_starvation"]
    # the single-rank seed + slow ring diffusion MUST strand ranks far
    # from rank 0 — and the verdict names them
    assert starve, "skewed run fired no rank_starvation"
    assert all("rank" in e and "window_nodes" in e for e in starve)
    assert bal["starved_ranks"], "balance block names no starved rank"
    assert set(bal["starved_ranks"]) == {e["rank"] for e in starve}
    assert bal["starvation_episodes"] == len(starve)
    # rank 0 held all seeds: it must be the straggler, not the starved
    assert bal["straggler_rank"] == 0
    assert 0 not in bal["starved_ranks"]
    assert bal["nodes_cv"] > 0.1


def test_rank_series_absent_under_tsp_obs_off():
    obs.set_enabled(False)
    res = _solve_skewed(max_iters=64)
    assert res.rank_series is None and res.rank_balance is None
    assert res.anomalies is None


@pytest.mark.chaos
def test_rank_stats_coherent_through_spill_fetch_faults():
    """Injected transient spill.fetch faults (absorbed by the bounded
    retry) must not desynchronize the per-rank accounting from the
    aggregate counters — the chaos guarantee for the rank view."""
    faults.clear()
    try:
        faults.configure("spill.fetch:raise,nth=2,count=2")
        res = _solve_skewed()
        hits = faults.registry().hits("spill.fetch")
    finally:
        faults.clear()
    assert res.proven_optimal
    assert hits > 2, "seam never crossed"
    assert HEALTH.snapshot()["retries"] >= 1  # the faults were absorbed
    bal = res.rank_balance
    assert sum(bal["nodes_per_rank"]) == res.nodes_expanded
    assert sum(bal["spill_bytes_to_host_per_rank"]) == res.spill_bytes_to_host
    assert (
        sum(bal["spill_bytes_to_device_per_rank"])
        == res.spill_bytes_to_device
    )
    assert sum(bal["spill_events_per_rank"]) == res.spill_events


# -- driver payload golden schema ----------------------------------------------

RANK_SERIES_SCHEMA = {
    "columns": list, "ranks": int, "window": int, "rows": list,
    "samples_total": int, "samples_dropped": int,
}

RANK_BALANCE_SCHEMA = {
    "ranks": int, "nodes_per_rank": list, "nodes_total": int,
    "nodes_cv": float, "nodes_max_min_ratio": float,
    "occupancy_mean": list, "occupancy_cv": float,
    "straggler_rank": int, "straggler_score": float,
    "starved_ranks": list, "starvation_episodes": int,
    "spill_events_per_rank": list, "spill_bytes_to_host_per_rank": list,
    "spill_bytes_to_device_per_rank": list, "reservoir_per_rank": list,
}


def _payload(res, inst):
    spec = importlib.util.spec_from_file_location(
        "bnb_solve", REPO / "tools" / "bnb_solve.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    class Args:
        ranks = 4
        bound = "min-out"
        mst_kernel = "prim"
        step_kernel = "reference"
        push_order = "best-first"
        push_block = 0
        balance = "ring"

    return mod.result_payload(res, inst, Args())


def test_sharded_payload_golden_schema():
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.resolve_instance("random:11:33")
    res = bb.solve_sharded(
        np.rint(inst.distance_matrix() * 10), make_rank_mesh(4), **SKEW_KW
    )
    payload = _payload(res, inst)
    for key, typ in RANK_SERIES_SCHEMA.items():
        assert key in payload["rank_series"], key
        assert isinstance(payload["rank_series"][key], typ), key
    bal = payload["obs"]["rank_balance"]
    for key, typ in RANK_BALANCE_SCHEMA.items():
        assert key in bal, key
        assert isinstance(bal[key], typ), (key, type(bal[key]))
    json.dumps(payload)  # one encodable JSON line, driver contract


# -- obs_report --ranks --------------------------------------------------------


def _obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", REPO / "tools" / "obs_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_ranks_renders_heatmap(tmp_path, capsys):
    res = _solve_skewed()
    from tsp_mpi_reduction_tpu.utils import tsplib

    inst = tsplib.resolve_instance("random:11:33")
    path = tmp_path / "payload.json"
    path.write_text(json.dumps(_payload(res, inst)))
    mod = _obs_report()
    rc = mod.main(["--ranks", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "4 ranks" in out and "occupancy heatmap" in out
    assert "straggler rank 0" in out
    for r in range(4):
        assert f"rank {r}" in out


def test_obs_report_ranks_errors_on_single_rank_payload(tmp_path, capsys):
    # a payload WITHOUT rank_series (single-rank run) must exit 2 with a
    # clear message — not render an empty healthy-looking section
    path = tmp_path / "single.json"
    path.write_text(json.dumps({"instance": "x", "rank_series": None}))
    mod = _obs_report()
    rc = mod.main(["--ranks", str(path)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "rank_series" in err and "single-rank" in err


def test_shard_bench_metrics_are_governed():
    from tsp_mpi_reduction_tpu.obs.bench_history import DEFAULT_RULES

    for name in ("shard_rank_obs_overhead", "shard_rank_us_per_dispatch"):
        rule = DEFAULT_RULES[name]
        assert rule.direction == "lower"
        assert rule.abs_threshold > 0  # percent/us near zero: absolute band

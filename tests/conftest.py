"""Test environment: CPU backend with 8 virtual devices + float64.

The JAX analog of the reference's single-rank-MPI-stub test trick
(SURVEY.md section 4): `--xla_force_host_platform_device_count=8` gives an
8-device mesh without hardware, so every sharding/collective path is exercised
in CI exactly as it would run on an 8-chip slice. float64 is enabled because
oracle parity is checked bit-for-bit against the C++ double-precision
reference (the TPU speed path, by contrast, runs float32).
"""

import os

# NOTE: in this image, sitecustomize imports jax at interpreter startup and
# registers the remote-TPU ("axon") backend, with JAX_PLATFORMS=axon already
# in the environment. Env edits here are therefore too late — jax read the
# env at its (startup) import. Force the platform through jax.config and
# deregister the axon factory so tests can never touch (or hang on) the
# remote-TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import pathlib  # noqa: E402

import pytest  # noqa: E402

GOLDENS = pathlib.Path(__file__).resolve().parent.parent / "goldens"


@pytest.fixture(scope="session")
def goldens_dir() -> pathlib.Path:
    return GOLDENS

"""Test environment: CPU backend with 8 virtual devices + float64.

The JAX analog of the reference's single-rank-MPI-stub test trick
(SURVEY.md section 4): `--xla_force_host_platform_device_count=8` gives an
8-device mesh without hardware, so every sharding/collective path is exercised
in CI exactly as it would run on an 8-chip slice. float64 is enabled because
oracle parity is checked bit-for-bit against the C++ double-precision
reference (the TPU speed path, by contrast, runs float32).
"""

import os

# tests exercise the bench helpers in-process; their runs must never
# append to the repo's real bench_history.jsonl (ISSUE 9) — tests that
# test the history layer point TSP_BENCH_HISTORY at a tmp path themselves
os.environ.setdefault("TSP_BENCH_HISTORY", "off")

import jax  # noqa: E402

from tsp_mpi_reduction_tpu.utils.backend import force_host_platform  # noqa: E402

force_host_platform(8)
jax.config.update("jax_enable_x64", True)

import pathlib  # noqa: E402

import pytest  # noqa: E402

GOLDENS = pathlib.Path(__file__).resolve().parent.parent / "goldens"


@pytest.fixture(scope="session")
def goldens_dir() -> pathlib.Path:
    return GOLDENS


@pytest.fixture(autouse=True)
def _reset_health_counters():
    """Per-test snapshot boundary for the registry-backed health counters
    (ISSUE 6 satellite): the counters are process-global, so without this
    reset back-to-back tests (and the serve sessions inside them) would
    see each other's recovery counts."""
    from tsp_mpi_reduction_tpu.perf import compile_cache
    from tsp_mpi_reduction_tpu.resilience.health import HEALTH

    HEALTH.reset_for_testing()
    # the always-on in-process ascent memo (ISSUE 13) must not leak hits
    # into tests that assert cold-memo behavior
    compile_cache.ascent_memo_reset_memory()
    yield

"""Fused Pallas expansion step (ISSUE 8): fused == reference parity.

The fused kernel (ops.expand_pallas.push_rows) shares every screen /
ordering / prefix-sum computation with the reference step and replaces
only the candidate-block materialize + compacting gather + block write
with an in-place Pallas row store. These tests pin the contract that
makes it adoptable: BIT-IDENTICAL search state — same pops, same pushed
set (live prefix rows equal word-for-word), same incumbent cost/tour,
same certified LB — on single steps, on multi-step solves (eil51 and a
kroA100 budgeted prefix), through donation, and under both push orders.
CPU runs exercise the kernel via Pallas INTERPRET mode, so tier-1
covers it without a TPU.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tsp_mpi_reduction_tpu.analysis import contracts
from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.ops import expand_pallas
from tsp_mpi_reduction_tpu.utils import tsplib


def _instance(n, seed=0, integral=True):
    rng = np.random.default_rng(seed)
    xy = rng.uniform(0, 100, (n, 2))
    d = np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1))
    return np.rint(d * 10) if integral else d


def _warm_state(d, k, steps=3, push_order="best-first"):
    """A realistic mid-search frontier via reference steps from the root
    (both kernels must branch from the IDENTICAL state)."""
    n = d.shape[0]
    bd = bb._bound_setup(d, "one-tree", node_ascent=0, ascent="host")
    d64 = np.asarray(d, np.float64)
    tour = bb.nearest_neighbor_tour(d64)
    inc_cost = jnp.asarray(bb.tour_cost(d64, tour), jnp.float32)
    inc_tour = jnp.asarray(tour, jnp.int32)
    fr = bb.make_root_frontier(
        n, 1024, np.asarray(bd.min_out, np.float64), pad_rows=k * n
    )
    args = (d, bd.min_out, bd.bound_adj, bd.dbar, bd.pi, bd.slack,
            bd.ascent_step, bd.lam_budget)
    d32 = jnp.asarray(d, jnp.float32)
    for _ in range(steps):
        fr, inc_cost, inc_tour, _ = bb._expand_step(
            fr, inc_cost, inc_tour, d32, *args[1:], k, n, bd.integral,
            False, 0, "prim", push_order, 0, "reference",
        )
    return fr, inc_cost, inc_tour, bd


def _step(fr, inc_cost, inc_tour, bd, d, k, push_order, step_kernel,
          use_mst=False):
    n = d.shape[0]
    return bb._expand_step(
        fr, inc_cost, inc_tour, jnp.asarray(d, jnp.float32), bd.min_out,
        bd.bound_adj, bd.dbar, bd.pi, bd.slack, bd.ascent_step,
        bd.lam_budget, k, n, bd.integral, use_mst, 0, "prim", push_order,
        0, step_kernel,
    )


def _copy(fr):
    # fresh leaves throughout: the step donates the WHOLE Frontier arg,
    # so a shared overflow scalar would be consumed by the first branch
    return bb.Frontier(fr.nodes + 0, fr.count + 0, fr.overflow ^ False)


@pytest.mark.parametrize("push_order", ["best-first", "natural"])
@pytest.mark.parametrize("n", [8, 33])
def test_fused_step_bit_identical_to_reference(n, push_order):
    """Same pops, same pushed SET (live rows word-equal), same incumbent
    cost/tour and stats — across mask-word boundaries and both orders."""
    d = _instance(n, seed=n)
    k = 8
    fr, ic, it, bd = _warm_state(d, k, push_order=push_order)
    fr2 = _copy(fr)

    out_r = _step(_copy(fr), ic, it, bd, d, k, push_order, "reference")
    out_f = _step(fr2, ic, it, bd, d, k, push_order, "fused")
    fr_r, ic_r, it_r, st_r = out_r
    fr_f, ic_f, it_f, st_f = out_f
    assert int(fr_r.count) == int(fr_f.count)
    assert bool(fr_r.overflow) == bool(fr_f.overflow)
    cnt = int(fr_r.count)
    assert np.array_equal(
        np.asarray(fr_r.nodes[:cnt]), np.asarray(fr_f.nodes[:cnt])
    )
    assert float(ic_r) == float(ic_f)
    assert np.array_equal(np.asarray(it_r), np.asarray(it_f))
    for key in st_r:
        assert int(st_r[key]) == int(st_f[key]), key


def test_fused_step_with_mst_screen_bit_identical():
    """The strong-bound screen (use_mst) feeds both kernels the same
    flags/columns — parity must survive it."""
    d = _instance(12, seed=7)
    k = 6
    fr, ic, it, bd = _warm_state(d, k)
    fr2 = _copy(fr)
    out_r = _step(_copy(fr), ic, it, bd, d, k, "best-first", "reference",
                  use_mst=True)
    out_f = _step(fr2, ic, it, bd, d, k, "best-first", "fused", use_mst=True)
    cnt = int(out_r[0].count)
    assert cnt == int(out_f[0].count)
    assert np.array_equal(
        np.asarray(out_r[0].nodes[:cnt]), np.asarray(out_f[0].nodes[:cnt])
    )
    assert float(out_r[1]) == float(out_f[1])


def _solve_fields(res):
    return (
        res.cost, res.proven_optimal, res.nodes_expanded, res.iterations,
        round(res.lower_bound, 6), round(res.lower_bound_raw, 6),
        tuple(int(x) for x in res.tour),
    )


def test_fused_solve_eil51_budgeted_prefix_bit_identical():
    """ISSUE 8 acceptance: identical incumbent, certified LB and
    proven status on an eil51 config, fused (interpret) vs reference —
    the search trajectories coincide step for step, so every reported
    field matches at the shared stopping point."""
    d = tsplib.embedded("eil51").distance_matrix()
    kw = dict(capacity=1 << 12, k=64, inner_steps=8, max_iters=128,
              node_ascent=0, device_loop=False, ils_rounds=0)
    res_r = bb.solve(d, step_kernel="reference", **kw)
    res_f = bb.solve(d, step_kernel="fused", **kw)
    assert _solve_fields(res_r) == _solve_fields(res_f)


def test_fused_solve_kroa100_budgeted_prefix_bit_identical():
    """Same acceptance on the kroA100 scale config (n=100: 25 path
    words, 4 mask words — the deep-row layout), tiny step budget."""
    d = tsplib.embedded("kroA100").distance_matrix()
    kw = dict(capacity=1 << 12, k=16, inner_steps=4, max_iters=12,
              mst_prune=False, node_ascent=0, device_loop=False,
              ils_rounds=0)
    res_r = bb.solve(d, step_kernel="reference", **kw)
    res_f = bb.solve(d, step_kernel="fused", **kw)
    assert _solve_fields(res_r) == _solve_fields(res_f)


def test_fused_small_proof_matches_reference_end_to_end():
    """A full proven-optimal run (random n=9): both kernels prove the
    SAME optimum with the SAME node count."""
    d = _instance(9, seed=3)
    kw = dict(capacity=1 << 10, k=8, inner_steps=4, max_iters=50_000,
              node_ascent=0, device_loop=False)
    res_r = bb.solve(d, step_kernel="reference", **kw)
    res_f = bb.solve(d, step_kernel="fused", **kw)
    assert res_r.proven_optimal and res_f.proven_optimal
    assert _solve_fields(res_r) == _solve_fields(res_f)


def test_fused_step_consumes_donated_frontier():
    """The fused path must keep the engine's donation discipline: the
    caller's buffer handle is dead after the dispatch (in-place alias,
    not a copy) — contracts.check_donated's invariant."""
    d = _instance(8, seed=1)
    k = 4
    fr, ic, it, bd = _warm_state(d, k)
    prev = fr.nodes
    out = _step(fr, ic, it, bd, d, k, "best-first", "fused")
    assert out[0].count is not None
    contracts.check_donated(prev, where="test.fused")
    assert prev.is_deleted()


def test_fused_rejects_push_block_and_bad_kernel():
    d = _instance(8, seed=1)
    k = 4
    fr, ic, it, bd = _warm_state(d, k)
    n = d.shape[0]
    args = (jnp.asarray(d, jnp.float32), bd.min_out, bd.bound_adj, bd.dbar,
            bd.pi, bd.slack, bd.ascent_step, bd.lam_budget)
    with pytest.raises(ValueError, match="push_block is a reference"):
        bb._expand_step(fr, ic, it, *args, k, n, bd.integral, False, 0,
                        "prim", "best-first", 64, "fused")
    with pytest.raises(ValueError, match="unknown step_kernel"):
        bb._expand_step(fr, ic, it, *args, k, n, bd.integral, False, 0,
                        "prim", "best-first", 0, "mosaic")


def test_push_rows_layout_constants_in_sync():
    assert expand_pallas.PATH_PACK == bb.PATH_PACK
    # the mask OR table must equal the engine's (int32 view)
    for n in (5, 33, 100):
        _, _, _, set_bit = bb._mask_consts(n)
        assert np.array_equal(
            expand_pallas._set_bit_words(n),
            np.asarray(set_bit).view(np.int32),
        )


def test_push_rows_width_mismatch_raises():
    nodes = jnp.zeros((32, 9), jnp.int32)  # n=8 width is 2+1+4=7, not 9
    with pytest.raises(ValueError, match="row width"):
        expand_pallas.push_rows(
            nodes, jnp.zeros((2, 9), jnp.int32), jnp.zeros((2, 8), jnp.int32),
            jnp.zeros((2, 8), jnp.float32), jnp.zeros((2, 8), jnp.float32),
            jnp.zeros((2, 8), jnp.float32), 8,
        )


# -- packed-layout runtime contract -------------------------------------------


def _frontier_from_fields(n, path, mask, depth, cost, bound, sm, count):
    rows = bb._pack_rows_np(path, mask, depth, cost, bound, sm)
    return bb.Frontier(
        jnp.asarray(rows), jnp.asarray(count, jnp.int32), jnp.asarray(False)
    )


def test_check_frontier_packed_accepts_valid(monkeypatch):
    monkeypatch.setenv("TSP_CONTRACTS", "strict")
    n = 10
    rng = np.random.default_rng(0)
    path = rng.integers(0, n, size=(4, n)).astype(np.int32)
    fr = _frontier_from_fields(
        n, path, np.zeros((4, 1), np.uint32), np.full(4, n, np.int32),
        np.zeros(4, np.float32), np.zeros(4, np.float32),
        np.zeros(4, np.float32), 4,
    )
    contracts.check_frontier_packed(fr, n, where="test")


def test_check_frontier_packed_rejects_corrupt_bytes(monkeypatch):
    monkeypatch.setenv("TSP_CONTRACTS", "strict")
    n = 10
    path = np.zeros((2, n), np.int32)
    fr = _frontier_from_fields(
        n, path, np.zeros((2, 1), np.uint32), np.full(2, 3, np.int32),
        np.zeros(2, np.float32), np.zeros(2, np.float32),
        np.zeros(2, np.float32), 2,
    )
    # a city id >= n inside a live prefix
    bad = np.asarray(fr.nodes).copy()
    bad[0, 0] = int(n + 5)  # byte 0 of word 0 = city at prefix position 0
    with pytest.raises(contracts.ContractError, match="city id"):
        contracts.check_frontier_packed(
            bb.Frontier(jnp.asarray(bad), fr.count, fr.overflow), n,
            where="test",
        )
    # a non-zero pad lane past n
    bad2 = np.asarray(fr.nodes).copy()
    bad2[0, bb._path_words(n) - 1] |= np.int32(1 << 24)  # lane 11 of 12
    with pytest.raises(contracts.ContractError, match="pad lanes"):
        contracts.check_frontier_packed(
            bb.Frontier(jnp.asarray(bad2), fr.count, fr.overflow), n,
            where="test",
        )


def test_check_frontier_packed_width_n_mismatch():
    n = 10
    fr = _frontier_from_fields(
        n, np.zeros((2, n), np.int32), np.zeros((2, 1), np.uint32),
        np.ones(2, np.int32), np.zeros(2, np.float32),
        np.zeros(2, np.float32), np.zeros(2, np.float32), 2,
    )
    with pytest.raises(contracts.ContractError, match="row width"):
        contracts.check_frontier_packed(fr, 50, where="test")


# -- checkpoint format: layout version + legacy migration ---------------------


def test_checkpoint_header_carries_layout_version(tmp_path):
    from tsp_mpi_reduction_tpu.resilience import checkpoint as store

    d = _instance(8, seed=2)
    fr, ic, it, bd = _warm_state(d, 4)
    path = str(tmp_path / "ck.npz")
    bb.save(path, fr, ic, it, d=d, bound="one-tree")
    header = store.read_header(path)
    assert header["frontier_layout"] == bb.FRONTIER_LAYOUT_VERSION
    # and the snapshot restores
    fr2, ic2, it2, rv, lb = bb.restore(path, expect_d=d,
                                       expect_bound="one-tree")
    assert int(fr2.count) == int(fr.count)
    assert np.array_equal(
        np.asarray(fr2.nodes[: int(fr.count)]),
        np.asarray(fr.nodes[: int(fr.count)]),
    )


def test_legacy_unpacked_snapshot_restores_through_store(tmp_path):
    """Migration (ISSUE 8 satellite): a checkpoint whose npz was written
    by the v1 engine (logical fields, no frontier_layout header key —
    emulated by packing the payload without the extra header) must
    restore into the v2 packed layout via read_with_fallback and resume
    to the proven optimum."""
    import io

    from tsp_mpi_reduction_tpu.resilience import checkpoint as store

    d = _instance(8, seed=5)
    n = d.shape[0]
    w = 1
    rng = np.random.default_rng(0)
    m = 3
    # hand-build a v1-era LOGICAL payload (the .npz schema is the stable
    # format both engines share)
    fields = {
        "path": rng.integers(0, n, size=(m, n)).astype(np.int32),
        "mask": np.ones((m, w), np.uint32),
        "depth": np.full(m, 2, np.int32),
        "cost": np.zeros(m, np.float32),
        "bound": np.asarray([5.0, 7.0, 6.0], np.float32),
        "sum_min": np.zeros(m, np.float32),
    }
    tour = bb.nearest_neighbor_tour(np.asarray(d, np.float64))
    payload = dict(
        inc_cost=np.asarray(1e9, np.float32),
        inc_tour=np.asarray(tour, np.int32),
        count=np.asarray(m),
        overflow=np.asarray(False),
        bound_mode=np.asarray("one-tree"),
        **fields,
    )
    path = str(tmp_path / "legacy.npz")
    # v1 writer: TSPCKPT header WITHOUT the frontier_layout key
    store.write_atomic(
        path, store.npz_bytes(**payload),
        fingerprint=store.instance_fingerprint(d),
    )
    assert "frontier_layout" not in (store.read_header(path) or {})
    fr, ic, it, rv, lb = bb.restore(path, expect_d=d,
                                    expect_bound="one-tree")
    # restored rows are v2-packed and carry the exact v1 logical fields
    assert np.array_equal(
        bb._unpack_rows_np(np.asarray(fr.nodes), n=n)["path"], fields["path"]
    )
    assert np.array_equal(np.asarray(fr.bound), fields["bound"])

    # and a truly headerless bare-npz file (the pre-resilience format)
    # still reads through the fallback path
    bare = str(tmp_path / "bare.npz")
    buf = io.BytesIO()
    np.savez_compressed(buf, **payload)
    with open(bare, "wb") as f:
        f.write(buf.getvalue())
    fr_b, *_ = bb.restore(bare, expect_d=d, expect_bound="one-tree")
    assert np.array_equal(np.asarray(fr_b.nodes), np.asarray(fr.nodes))


#: end-to-end migration body, run in a FRESH subprocess: checkpoint a
#: budget-capped run, strip the layout header (v1 writer emulation),
#: resume through BOTH step kernels, require identical proven optima.
#: Subprocess isolation is deliberate: tier-1's in-process CLI tests
#: (tests/test_cli.py run_cli) leave the jax/MLIR runtime in a state
#: where a LATER fresh lowering can abort in make_ir_context — a
#: pre-existing, order-dependent environment fault this repo's layout
#: predates (reproduced on the unmodified parent commit); a fresh
#: process sidesteps it without weakening the migration check.
_MIGRATION_SCRIPT = r"""
import sys
import numpy as np
from tsp_mpi_reduction_tpu.models import branch_bound as bb
from tsp_mpi_reduction_tpu.resilience import checkpoint as store

ck = sys.argv[1]
rng = np.random.default_rng(11)
xy = rng.uniform(0, 100, (12, 2))
d = np.rint(np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1)) * 10)
kw = dict(capacity=1 << 10, k=8, inner_steps=2, mst_prune=False,
          node_ascent=0, ils_rounds=0, device_loop=False)
res0 = bb.solve(d, max_iters=2, checkpoint_path=ck, **kw)
assert not res0.proven_optimal
header, payload, _src, _fb = store.read_with_fallback(ck)
store.write_atomic(ck, payload, fingerprint=header.get("fingerprint"))
assert "frontier_layout" not in (store.read_header(ck) or {})
results = []
for kernel in ("reference", "fused"):
    res = bb.solve(d, max_iters=500_000, resume_from=ck,
                   step_kernel=kernel, **kw)
    assert res.proven_optimal
    results.append((res.cost, res.nodes_expanded, res.iterations,
                    tuple(int(x) for x in res.tour)))
assert results[0] == results[1], results
print("MIGRATION_OK", results[0][0])
"""


def test_resume_legacy_snapshot_solves_to_optimum(tmp_path):
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", _MIGRATION_SCRIPT, str(tmp_path / "mig.npz")],
        capture_output=True, text=True, timeout=480, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "MIGRATION_OK" in r.stdout


def test_fused_sharded_solve_matches_reference():
    """step_kernel threads through the shard_map rank bodies: a 4-rank
    sharded proof is identical under both kernels (the Pallas interpret
    path composes with shard_map on the CPU virtual mesh)."""
    from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh

    d = _instance(10, seed=5)
    mesh = make_rank_mesh(4)
    kw = dict(capacity_per_rank=512, k=8, inner_steps=4, max_iters=200_000,
              node_ascent=0, device_loop=False)
    res_r = bb.solve_sharded(d, mesh, step_kernel="reference", **kw)
    res_f = bb.solve_sharded(d, mesh, step_kernel="fused", **kw)
    assert res_r.proven_optimal and res_f.proven_optimal
    assert res_r.cost == res_f.cost
    assert res_r.nodes_expanded == res_f.nodes_expanded
    assert np.array_equal(res_r.tour, res_f.tour)

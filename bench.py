"""Benchmark driver. Prints ONE JSON line: {"metric","value","unit","vs_baseline"}.

Two modes, selected by ``TSP_BENCH`` (default ``pipeline``):

- ``pipeline`` — full blocked pipeline, 16 cities x 100 blocks (headline
  config). Baseline: the unmodified reference solving the same
  deterministic instance single-rank takes 69997 ms (BASELINE.md, measured
  in this environment at g++ -O2; identical instance because generation is
  srand(0)-deterministic). ``vs_baseline`` = baseline_ms / ours.
  Method: device pipeline in float32 (TPU speed mode) — on-device distance
  matrix, vmapped dense Held-Karp over all 100 blocks, then the merge fold.
  BOTH fold shapes are measured and the faster is reported (disclosed via
  the JSON ``fold`` key): the log2(B) TREE of vmapped pairwise merges
  (fold_tours_tree — the shape of the reference's own cross-rank
  MPI_ManualReduce; the merge operator is non-associative, so the folded
  cost legitimately differs from the sequential within-rank fold exactly as
  the reference's output differs across rank counts) and the sequential
  scan fold (the reference's rank-local order, tsp.cpp:348-352).

- ``bnb`` — the north-star metric (BASELINE.json): B&B nodes/sec on a
  TSPLIB instance solved to PROVEN optimality. Default instance: eil51
  (426) — berlin52's Held-Karp root bound equals its optimum, so with the
  ILS incumbent it closes at the root in 1 node and has no throughput to
  measure; eil51's bound genuinely gaps (~422.5 vs 426), forcing a real
  search. The reference has no B&B and no TSPLIB mode (SURVEY.md §0
  discrepancy note), so there is no reference binary to time; the baseline
  anchor is this engine's own single-rank CPU rate x8 — a stand-in for the
  north star's "8-rank MPI" comparison that generously assumes perfect MPI
  scaling (BNB_CPU_8RANK_ANCHOR below, measured on this host).
  ``vs_baseline`` = device nodes/sec / anchor.

TIMING METHODOLOGY (critical on this image's remote-TPU relay): the first
device->host transfer of the process permanently degrades dispatch latency
(~65 ms per dispatch slice; lax.while_loop programs pay it PER ITERATION —
a measured 660x slowdown on the B&B kernel), and ``block_until_ready`` does
not actually block. Plain per-call timing is therefore wrong in BOTH
directions. This bench instead:

- pipeline: chains M dependent executions (each run's scalar output feeds
  the next run's input) and reads back ONE value at the end — the read
  drains the whole queue, so wall/M is a true per-run time; the runs
  themselves execute in the relay's fast (pre-transfer) mode.
- bnb: runs the whole search as ONE device dispatch
  (branch_bound._solve_device, transfer-free setup) and AOT-compiles the
  kernel first (warm_compile_device_solver) so the timed dispatch excludes
  compilation without a poisoning warmup execution.

Compile time is excluded in both modes (the reference has no JIT; with the
persistent compilation cache it is a one-time cost) and printed to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_MS = 69997.0  # BASELINE.md: 16 cities/block x 100 blocks, 1 rank
N, BLOCKS, GRID = 16, 100, 1000

#: Single-rank CPU B&B nodes/sec on eil51 (this engine, this host,
#: proven-optimal run, compile excluded) x 8 ranks — i.e. the anchor
#: generously assumes perfect 8-way MPI scaling of our own CPU rate.
#: Measured 2026-07-30 at the current engine config (k=1024, node_ascent=2,
#: f64 host ascent): 16,283 nodes/s, proof in 9.4 s; see BENCHMARKS.md.
#: CAVEAT: a point host measurement — BENCHMARKS.md documents ±8% run-to-run
#: drift on this shared host, so vs_baseline inherits that error bar.
BNB_CPU_8RANK_ANCHOR = 8 * 16283.0

#: fold names accepted by TSP_BENCH_FOLD, in measurement order.
#: tree_xy_polish = the fastest fold + an on-device polish (alternating
#: best-improvement 2-opt and Or-opt sweeps) of the final tour — the
#: non-associative fold order makes tree tours ~10% costlier than scan
#: tours formulaically (BENCH_TPU_PIPELINE r4), and the polish converts
#: that into a measured-length win the reference cannot reach at any
#: fold order (CPU: 31,314 vs the reference's true ~36,405)
VALID_FOLDS = ("tree_xy", "tree", "scan", "tree_xy_polish")

#: alternation cap for the polish fold's 2-opt + Or-opt rounds (each
#: constituent sweep is monotone; the while_loop exits at convergence —
#: measured converged by round 6 on the 16x100 tour)
POLISH_MAX_ROUNDS = 6


def _accelerator_usable(timeout_s: float = 180.0) -> bool:
    """Bounded probe for a usable accelerator; the real implementation moved
    to utils.backend.accelerator_usable (round 5) so every entry point —
    CLI, bnb_solve, sweep, profilers — shares the dead-grant hang guard this
    bench always had, not just bench.py."""
    from tsp_mpi_reduction_tpu.utils.backend import accelerator_usable

    return accelerator_usable(timeout_s)


def bench_bnb() -> int:
    """North-star metric: B&B nodes/sec to proven optimality (default
    instance eil51 — see module docstring for why not berlin52)."""
    import jax

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)
    name = os.environ.get("TSP_BENCH_INSTANCE", "eil51")
    inst = tsplib.embedded(name)
    d = inst.distance_matrix()
    n = d.shape[0]
    k = int(os.environ.get("TSP_BENCH_K", "1024"))
    capacity = max(1 << 17, 8 * k * (n - 1))
    # per-node mini-ascent depth: more steps = fewer nodes but more Prims
    # per pop; the best time-to-proof point is hardware-dependent
    na = int(os.environ.get("TSP_BENCH_NODE_ASCENT", "2"))
    # MST bound kernel: prim (sequential jnp chain), boruvka (log-depth
    # batched rounds — recorded negative result), or prim_pallas (the
    # whole chain fused into one Pallas kernel — 0.74 vs 2.92 ms per
    # bound eval on a v5e). Default: prim_pallas on TPU backends for
    # n <= 128 (the COMPILED kernel's lane limit — 256 lanes are
    # interpret-only, prim_pallas.py docstring), falling back to prim for
    # larger instances and everywhere off-TPU (interpret mode would be
    # slower than jnp on CPU).
    on_cpu = jax.default_backend() == "cpu"
    on_tpu = jax.default_backend() == "tpu"
    mk = os.environ.get(
        "TSP_BENCH_MST_KERNEL",
        "prim_pallas" if (on_tpu and n <= 128) else "prim",
    )
    # push ordering: "best-first" (default) or "natural" (skip the
    # per-step two-level sort: cheaper steps, possibly more nodes — on
    # eil51 the ILS start is not optimal, so pop order does shape the
    # tree; BENCH_BNB_TPU_R5_NOSORT.json is the on-chip A/B verdict)
    po = os.environ.get("TSP_BENCH_PUSH_ORDER", "best-first")
    # capped push-block rows (0 = full k*n; scatter_profile v4 sizes it)
    pb = int(os.environ.get("TSP_BENCH_PUSH_BLOCK", "0"))
    if mk not in bb._MST_CONN:
        print(
            f"bench: TSP_BENCH_MST_KERNEL={mk!r} is not one of "
            f"{sorted(bb._MST_CONN)}", file=sys.stderr,
        )
        return 2

    t0 = time.perf_counter()
    if on_cpu:
        # no relay, no poison: a tiny warmup run compiles the host-loop
        # kernels; the fine-grained host loop also honors time_limit_s
        bb.solve(d, capacity=capacity, k=k, node_ascent=na,
                 device_loop=False, max_iters=8, mst_kernel=mk,
                 push_order=po, push_block=pb)
    else:
        # AOT compile only (no device execution -> the relay stays in fast
        # mode); integral must match what _bound_setup will derive from
        # the data or the timed dispatch recompiles a new static config
        bb.warm_compile_device_solver(
            n, capacity, k, bb._is_integral(d), True, na, mst_kernel=mk,
            push_order=po, push_block=pb,
        )
    print(f"warmup (compile): {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    res = bb.solve(
        d, capacity=capacity, k=k, time_limit_s=600, node_ascent=na,
        device_loop=not on_cpu, max_iters=5_000_000, mst_kernel=mk,
        push_order=po, push_block=pb,
    )
    ok = res.proven_optimal and res.cost == inst.known_optimum
    print(
        f"{name}: cost={res.cost} (known {inst.known_optimum}) "
        f"proven={res.proven_optimal} nodes={res.nodes_expanded} "
        f"wall={res.wall_seconds:.2f}s setup={res.setup_seconds:.1f}s "
        f"(ascent {res.ascent_seconds:.1f} + ils {res.ils_seconds:.1f} + "
        f"backend {res.setup_seconds - res.ascent_seconds - res.ils_seconds:.1f})",
        file=sys.stderr,
    )
    if not ok:
        print("bench: WARNING — run did not prove the known optimum", file=sys.stderr)
    value = res.nodes_per_sec
    print(
        json.dumps(
            {
                "metric": f"bnb_{name}_nodes_per_sec",
                "value": round(value, 1),
                "unit": "nodes/s",
                "vs_baseline": round(value / BNB_CPU_8RANK_ANCHOR, 2),
                "proven_optimal": bool(res.proven_optimal),
                "device": "cpu" if on_cpu else str(dev),
                # time-to-proof is the robust cross-engine number
                # (nodes/sec across engines with different bounds is
                # apples-to-oranges); anchor caveat made explicit. None
                # when the run stopped without a proof — a finite value
                # must never describe a proof that didn't happen
                "time_to_proof_s": (
                    round(res.setup_seconds + res.wall_seconds, 2)
                    if res.proven_optimal
                    else None
                ),
                "setup_s": round(res.setup_seconds, 2),
                "setup_ascent_s": round(res.ascent_seconds, 2),
                "setup_ils_s": round(res.ils_seconds, 2),
                "mst_kernel": mk,
                "push_order": po,
                "push_block": pb,
                "anchor": (
                    "this engine's own 1-rank CPU rate x8 "
                    "(assumes perfect 8-way MPI scaling)"
                ),
            }
        )
    )
    return 0


def main() -> int:
    if (
        os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
        or os.environ.get("TSP_BENCH_PROBED") == "1"
    ):
        pass  # CPU pinned, or the parent bench already probed
    elif not _accelerator_usable():
        print(
            "bench: no usable accelerator; falling back to CPU "
            "(numbers will not reflect TPU performance)",
            file=sys.stderr,
        )
        from tsp_mpi_reduction_tpu.utils.backend import select_backend

        select_backend("cpu")

    bnb_mode = os.environ.get("TSP_BENCH", "pipeline") == "bnb"
    fold_pin = os.environ.get("TSP_BENCH_FOLD")
    if not bnb_mode and fold_pin is not None and fold_pin not in VALID_FOLDS:
        print(
            f"bench: ignoring unrecognized TSP_BENCH_FOLD={fold_pin!r} "
            f"(expected one of {VALID_FOLDS}); measuring all",
            file=sys.stderr,
        )
        fold_pin = None
    if not bnb_mode and fold_pin is None:
        # PARENT SPAWNER: each fold is measured in its own subprocess
        # (see the methodology comment below). The parent must NOT
        # initialize a jax backend — the remote-TPU claim is exclusive
        # per process, so a parent holding it would deadlock every child.
        return _spawn_fold_children()

    from tsp_mpi_reduction_tpu.utils.backend import enable_persistent_cache

    import jax

    enable_persistent_cache(jax.default_backend())

    if bnb_mode:
        return bench_bnb()
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.ops import held_karp
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix
    from tsp_mpi_reduction_tpu.ops.generator import generate_instance
    from tsp_mpi_reduction_tpu.ops.held_karp import build_plan, solve_blocks_from_dists
    from tsp_mpi_reduction_tpu.ops.local_search import polish, tour_length
    from tsp_mpi_reduction_tpu.ops.merge import (
        fold_tours,
        fold_tours_tree,
        fold_tours_tree_xy,
    )

    impl = os.environ.get("TSP_TPU_IMPL")  # compact|dense|fused|pallas
    if impl:
        held_karp.set_impl(impl)
        print(f"bench impl override: {impl}", file=sys.stderr)

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    _, xy = generate_instance(N, BLOCKS, GRID, GRID)
    xy32 = jnp.asarray(np.asarray(xy, np.float32))

    def make_step(fold, from_xy, do_polish):
        total = N * BLOCKS

        @jax.jit
        def step(xy_blocks, feedback):
            flat = xy_blocks.reshape(-1, 2)
            block_d = jax.vmap(distance_matrix)(xy_blocks)
            costs, local_tours = solve_blocks_from_dists(block_d, jnp.float32)
            offsets = (jnp.arange(BLOCKS, dtype=jnp.int32) * N)[:, None]
            ctx = flat if from_xy else distance_matrix(flat)
            ids, length, cost = fold(
                local_tours.astype(jnp.int32) + offsets, costs, ctx
            )
            # measured true length alongside the reference-semantics
            # formulaic cost (quirk #4: the splice is never re-measured)
            dist = ctx if not from_xy else distance_matrix(flat)
            t_open = ids[:total]  # drop the closing duplicate
            if do_polish:
                t_open, _ = polish(t_open, dist, max_rounds=POLISH_MAX_ROUNDS)
            measured = tour_length(t_open, dist)
            head = measured if do_polish else cost
            # feedback*0 threads the previous run's output into this run's
            # input: the M timed runs form one dependency chain, so a
            # single final readback drains them all (see module docstring)
            return head + feedback * 0.0, cost, measured
        return step

    def timed(name, fold, m, from_xy=False, do_polish=False):
        step = make_step(fold, from_xy, do_polish)
        t0 = time.perf_counter()
        c, _, _ = step(xy32, jnp.float32(0.0))  # compile+first run; no readback
        # block_until_ready does NOT block in the relay's fast mode, and
        # any true sync is a device->host transfer that would poison every
        # subsequent dispatch — so the warmup run's execution tail can
        # spill into the timed window below. The bias is bounded (<=1/m of
        # the window, shrinking with m) and conservative: it can only
        # OVERSTATE per-run time, never flatter it.
        jax.block_until_ready(c)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(m):
            c, cost, measured = step(xy32, c)
        v = float(c)  # ONE readback: drains the chained queue
        per_run = (time.perf_counter() - t0) * 1000.0 / m
        return per_run, v, compile_s, float(cost), float(measured)

    # CHILD: measure the one fold this process is pinned to (see
    # _spawn_fold_children for why folds are process-isolated): the tree
    # (log2(B) vmapped merge rounds — the shape of the reference's own
    # cross-rank reduce) removes the B-step sequential dependency chain;
    # tree_xy computes the swap costs from coordinates (no [N,N] random
    # gathers; same values as tree on CPU, ±1 ULP under TPU fusion — the
    # cost is printed so a flip is visible); the scan is the reference's
    # rank-local fold order. The merge operator is non-associative, so
    # tree and scan costs legitimately differ — exactly as the
    # reference's output differs across rank counts.
    folds = {
        "tree_xy": (fold_tours_tree_xy, True, False),
        "tree": (fold_tours_tree, False, False),
        "scan": (fold_tours, False, False),
        "tree_xy_polish": (fold_tours_tree_xy, True, True),
    }
    assert tuple(folds) == VALID_FOLDS  # parent/child fold sets in sync
    m = int(os.environ.get("TSP_BENCH_REPS", "20"))  # bias <= 1/m, see timed()
    fold, from_xy, do_polish = folds[fold_pin]
    ms, v, cs, cost, measured = timed(
        fold_pin, fold, m, from_xy=from_xy, do_polish=do_polish
    )
    print(
        f"{fold_pin}: {ms:.1f} ms/run over {m} chained runs "
        f"(compile+first {cs:.1f}s, cost={cost:.3f}, measured={measured:.3f})",
        file=sys.stderr,
    )
    plan = build_plan(N)
    nodes_per_sec = plan.dp_transitions * BLOCKS / (ms / 1000.0)
    print(f"dp_transitions/s={nodes_per_sec:.3e}", file=sys.stderr)
    print(_pipeline_json(ms, fold_pin, cost=v, measured=measured))
    return 0


def _pipeline_json(
    value_ms: float, fold: str, cost: float | None = None,
    folds: dict | None = None, measured: float | None = None,
) -> str:
    """One-line artifact. ``cost`` is the reported fold's headline cost
    (formulaic reference semantics for plain folds — quirk #4 — but the
    MEASURED length for the polish fold, whose point is true quality);
    ``measured`` is always the re-measured length of the final tour;
    ``folds`` carries every measured fold's {ms, cost, measured} so the
    speed/quality trade-off is in the JSON itself, not just stderr.
    Baseline cost for this instance: 34367.05 (the reference's own
    single-rank fold order, BASELINE.md 16x100 row)."""
    out = {
        "metric": "pipeline_16x100_wall_ms",
        "value": round(value_ms, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / value_ms, 2),
        "fold": fold,
    }
    if cost is not None:
        out["cost"] = round(cost, 3)
        out["baseline_cost"] = 34367.048
    if measured is not None:
        out["measured"] = round(measured, 3)
    if folds is not None:
        out["folds"] = folds
    return json.dumps(out)


def _spawn_fold_children() -> int:
    """Measure every fold shape, each in its own subprocess, and report
    the fastest. Process isolation matters twice on the remote relay:
    a process's first readback permanently degrades its later dispatches
    (so folds measured after another fold's drain would be biased), and
    the chip claim is exclusive per process (so this parent must never
    initialize a jax backend itself — children would deadlock)."""
    import subprocess

    results = {}
    for nm in VALID_FOLDS:
        env = dict(os.environ, TSP_BENCH_FOLD=nm, TSP_BENCH_PROBED="1")
        if env.get("JAX_PLATFORMS", "").strip() == "cpu":
            # CPU fallback: the axon sitecustomize would re-register the
            # remote plugin in the child and dial the dead tunnel anyway
            # (it overrides JAX_PLATFORMS) — disarm it entirely
            env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env, timeout=1200,
            )
        except subprocess.TimeoutExpired:
            # a lapsed chip grant hangs a fresh client init forever
            print(f"bench: fold {nm} subprocess timed out", file=sys.stderr)
            continue
        sys.stderr.write(r.stderr)
        try:
            child = json.loads(r.stdout.strip().splitlines()[-1])
            results[nm] = {
                "ms": float(child["value"]),
                "cost": child.get("cost"),
                "measured": child.get("measured"),
            }
        except (json.JSONDecodeError, IndexError, KeyError):
            print(f"bench: fold {nm} subprocess failed "
                  f"(rc={r.returncode})", file=sys.stderr)
    if not results:
        return 1
    best = min(results, key=lambda nm: results[nm]["ms"])
    print(_pipeline_json(
        results[best]["ms"], best, cost=results[best]["cost"],
        folds=results, measured=results[best].get("measured"),
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())

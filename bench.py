"""Benchmark driver. Prints ONE JSON line: {"metric","value","unit","vs_baseline"}.

Modes, selected by ``TSP_BENCH`` (default ``pipeline``):

- ``pipeline`` — full blocked pipeline, 16 cities x 100 blocks (headline
  config). Baseline: the unmodified reference solving the same
  deterministic instance single-rank takes 69997 ms (BASELINE.md, measured
  in this environment at g++ -O2; identical instance because generation is
  srand(0)-deterministic). ``vs_baseline`` = baseline_ms / ours.
  Method: device pipeline in float32 (TPU speed mode) — on-device distance
  matrix, vmapped dense Held-Karp over all 100 blocks, then the merge fold.
  BOTH fold shapes are measured and the faster is reported (disclosed via
  the JSON ``fold`` key): the log2(B) TREE of vmapped pairwise merges
  (fold_tours_tree — the shape of the reference's own cross-rank
  MPI_ManualReduce; the merge operator is non-associative, so the folded
  cost legitimately differs from the sequential within-rank fold exactly as
  the reference's output differs across rank counts) and the sequential
  scan fold (the reference's rank-local order, tsp.cpp:348-352).

- ``spill`` — reservoir transfer accounting on an 8-virtual-device CPU
  mesh (forced; the counters measure BYTES, not seconds): a tiny per-rank
  capacity drives constant spill traffic, and the JSON reports the
  measured host<->device bytes per spill round vs what the pre-PR-2
  full-buffer round trip (``np.asarray(fr.nodes)`` + ``device_put`` of
  the whole stacked buffer per spill) would have moved on the same run.

- ``serve`` — the serving-layer acceptance bench (ISSUE 3): micro-batched
  vs sequential single-instance throughput through the full
  ``tsp_mpi_reduction_tpu.serve`` service path on a same-shape workload,
  plus cache-hit rate on permuted/translated resubmission and the
  deadline ladder's behavior under an impossible budget. Also writes the
  ``BENCH_SERVE.json`` artifact (see :func:`bench_serve`).

- ``compile`` — the compile-once acceptance bench (ISSUE 5): cold vs warm
  process startup against one shared ``TSP_COMPILE_CACHE`` dir, measured
  in fresh subprocesses (chunk-resume startup through the device loop +
  serve first-flush latency), with cold/warm result equality asserted.
  Writes ``BENCH_COMPILE_CACHE.json`` (see :func:`bench_compile`;
  ``compile-child`` is its internal per-process mode).

- ``faults`` — atomic-checkpoint overhead vs the legacy direct write
  (ISSUE 4); writes ``BENCH_FAULTS.json`` (see :func:`bench_faults`).

- ``obs`` — the telemetry acceptance bench (ISSUE 6): full obs stack
  (metrics registry + span tracing to JSONL + per-dispatch sampler) vs
  ``TSP_OBS=off`` B&B wall overhead (acceptance <= 2%), plus serve
  span-tree completeness (zero orphan spans across a multi-request
  session with degraded + malformed requests). Writes ``BENCH_OBS.json``
  (see :func:`bench_obs`).

- ``fleet`` — the fleet serving acceptance bench (ISSUE 11): sustained
  RPS + p99 vs replica count 1/2/4 through the front + replica
  subprocess stack (clean, then under injected ``replica.kill``), plus
  the 3-replica/48-request chaos acceptance demo (kills + hangs,
  exactly-once answers, cross-replica cache hits, stitched traces).
  Writes ``BENCH_FLEET.json`` (see :func:`bench_fleet`).

- ``bnb`` — the north-star metric (BASELINE.json): B&B nodes/sec on a
  TSPLIB instance solved to PROVEN optimality. Default instance: eil51
  (426) — berlin52's Held-Karp root bound equals its optimum, so with the
  ILS incumbent it closes at the root in 1 node and has no throughput to
  measure; eil51's bound genuinely gaps (~422.5 vs 426), forcing a real
  search. The reference has no B&B and no TSPLIB mode (SURVEY.md §0
  discrepancy note), so there is no reference binary to time; the baseline
  anchor is this engine's own single-rank CPU rate x8 — a stand-in for the
  north star's "8-rank MPI" comparison that generously assumes perfect MPI
  scaling (BNB_CPU_8RANK_ANCHOR below, measured on this host).
  ``vs_baseline`` = device nodes/sec / anchor.

TIMING METHODOLOGY (critical on this image's remote-TPU relay): the first
device->host transfer of the process permanently degrades dispatch latency
(~65 ms per dispatch slice; lax.while_loop programs pay it PER ITERATION —
a measured 660x slowdown on the B&B kernel), and ``block_until_ready`` does
not actually block. Plain per-call timing is therefore wrong in BOTH
directions. This bench instead:

- pipeline: chains M dependent executions (each run's scalar output feeds
  the next run's input) and reads back ONE value at the end — the read
  drains the whole queue, so wall/M is a true per-run time; the runs
  themselves execute in the relay's fast (pre-transfer) mode.
- bnb: runs the whole search as ONE device dispatch
  (branch_bound._solve_device, transfer-free setup) and AOT-compiles the
  kernel first (warm_compile_device_solver) so the timed dispatch excludes
  compilation without a poisoning warmup execution.

Compile time is excluded in both modes (the reference has no JIT; with the
persistent compilation cache it is a one-time cost) and printed to stderr.

TIMEOUT RESILIENCE (round-5 regression BENCH_r05.json: rc=124, parsed null —
an external driver timeout killed the fold sweep mid-child and NO JSON line
was ever emitted): the pipeline parent now runs under a wall budget
(``TSP_BENCH_BUDGET_S``, default 600 s, measured from process start) — each
fold child gets at most the remaining budget, folds that don't fit are
skipped, and the final JSON line is ALWAYS printed, reporting whatever
completed (or an explicit error when nothing did). On a CPU fallback the
chained-run count per fold drops automatically (each chained run is ~20 s
there vs ~ms on-chip; the per-run number is unchanged, only its averaging
window shrinks); ``--quick`` / ``TSP_BENCH_QUICK=1`` additionally restricts
to the two cheap-compile folds for smoke runs.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

#: process-start anchor for the pipeline wall budget: the budget must cover
#: the accelerator probe too, or probe + folds can together outlive an
#: external driver timeout with no JSON emitted
_T0 = time.monotonic()

BASELINE_MS = 69997.0  # BASELINE.md: 16 cities/block x 100 blocks, 1 rank
N, BLOCKS, GRID = 16, 100, 1000

#: Single-rank CPU B&B nodes/sec on eil51 (this engine, this host,
#: proven-optimal run, compile excluded) x 8 ranks — i.e. the anchor
#: generously assumes perfect 8-way MPI scaling of our own CPU rate.
#: Measured 2026-07-30 at the current engine config (k=1024, node_ascent=2,
#: f64 host ascent): 16,283 nodes/s, proof in 9.4 s; see BENCHMARKS.md.
#: CAVEAT: a point host measurement — BENCHMARKS.md documents ±8% run-to-run
#: drift on this shared host, so vs_baseline inherits that error bar.
BNB_CPU_8RANK_ANCHOR = 8 * 16283.0

#: fold names accepted by TSP_BENCH_FOLD, in measurement order.
#: tree_xy_polish = the fastest fold + an on-device polish (alternating
#: best-improvement 2-opt and Or-opt sweeps) of the final tour — the
#: non-associative fold order makes tree tours ~10% costlier than scan
#: tours formulaically (BENCH_TPU_PIPELINE r4), and the polish converts
#: that into a measured-length win the reference cannot reach at any
#: fold order (CPU: 31,314 vs the reference's true ~36,405)
VALID_FOLDS = ("tree_xy", "tree", "scan", "tree_xy_polish")

#: alternation cap for the polish fold's 2-opt + Or-opt rounds (each
#: constituent sweep is monotone; the while_loop exits at convergence —
#: measured converged by round 6 on the 16x100 tour)
POLISH_MAX_ROUNDS = 6


def _history_append(mode: str, artifact: dict, config: dict | None = None) -> None:
    """Append this run's headline to ``bench_history.jsonl`` (ISSUE 9):
    every TSP_BENCH run leaves one fingerprinted record (git rev, jax
    version, backend, config hash, metric/value) so ``make bench-check``
    can gate on the trajectory, not just the latest artifact. Disabled
    with TSP_BENCH_HISTORY=off (the test suite does); never allowed to
    fail a bench — history is an observer."""
    try:
        from tsp_mpi_reduction_tpu.obs import bench_history as bh

        path = bh.resolve_history_path(os.path.dirname(os.path.abspath(__file__)))
        if path is None or artifact.get("metric") is None:
            return
        bh.append(path, bh.make_record(mode, artifact, config=config))
    except Exception as e:  # noqa: BLE001 — observer, not a gate
        print(f"bench: history append skipped ({e})", file=sys.stderr)


def _accelerator_usable(timeout_s: float = 180.0) -> bool:
    """Bounded probe for a usable accelerator; the real implementation moved
    to utils.backend.accelerator_usable (round 5) so every entry point —
    CLI, bnb_solve, sweep, profilers — shares the dead-grant hang guard this
    bench always had, not just bench.py."""
    from tsp_mpi_reduction_tpu.utils.backend import accelerator_usable

    return accelerator_usable(timeout_s)


def bench_faults() -> int:
    """Atomic-checkpoint overhead (ISSUE 4): the crash-safe store (in-memory
    npz -> header+checksum -> temp file + fsync + rotation + os.replace) vs
    the legacy direct ``np.savez_compressed`` on byte-identical payloads.
    Host-side IO only — forced CPU, never probes the accelerator. Emits
    ``BENCH_FAULTS.json`` and prints one JSON line (vs_baseline =
    direct_ms / atomic_ms: < 1 means the durability costs that factor)."""
    import tempfile
    import time

    import numpy as np

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.resilience import checkpoint as ck_store
    from tsp_mpi_reduction_tpu.utils import tsplib

    reps = int(os.environ.get("TSP_BENCH_FAULTS_REPS", "30"))
    out_path = os.environ.get("TSP_BENCH_FAULTS_OUT", "BENCH_FAULTS.json")
    d = tsplib.embedded("burma14").distance_matrix()
    workdir = tempfile.mkdtemp(prefix="bench_faults_")
    seed_ck = os.path.join(workdir, "seed.npz")
    # a real mid-search frontier (unproven -> the engine's final save runs)
    res = bb.solve(d, capacity=4096, k=64, inner_steps=4, max_iters=6,
                   bound="min-out", node_ascent=0, device_loop=False,
                   checkpoint_path=seed_ck)
    assert not res.proven_optimal, "seed run proved early; shrink max_iters"
    fr, ic, itour, _resv, lb = bb.restore(seed_ck, expect_d=d,
                                          expect_bound="min-out")
    payload = bb._ckpt_payload(fr, ic, itour, d=d, bound="min-out",
                               lb_floor=lb)
    atomic_path = os.path.join(workdir, "atomic.npz")
    direct_path = os.path.join(workdir, "direct.npz")

    t0 = time.perf_counter()
    for _ in range(reps):
        # the full production path: payload build + atomic publish
        bb.save(atomic_path, fr, ic, itour, d=d, bound="min-out", lb_floor=lb)
    atomic_ms = (time.perf_counter() - t0) / reps * 1000.0

    t0 = time.perf_counter()
    for _ in range(reps):
        _ = bb._ckpt_payload(fr, ic, itour, d=d, bound="min-out", lb_floor=lb)
        np.savez_compressed(direct_path, **payload)  # graftlint: disable=R6 — the measured legacy baseline
    direct_ms = (time.perf_counter() - t0) / reps * 1000.0

    artifact = {
        "metric": "atomic_checkpoint_overhead",
        "unit": "ms/save",
        "instance": "burma14",
        "payload_bytes": os.path.getsize(direct_path),
        "file_bytes": os.path.getsize(atomic_path),
        "reps": reps,
        "rotation_keep": ck_store.default_keep(),
        "direct_ms": round(direct_ms, 3),
        "atomic_ms": round(atomic_ms, 3),
        "overhead_ms": round(atomic_ms - direct_ms, 3),
        "overhead_pct": round((atomic_ms / direct_ms - 1.0) * 100.0, 1)
        if direct_ms
        else None,
        # what the overhead buys: integrity header + checksum + fsync +
        # last-N rotation + torn-write immunity at every byte offset
        "value": round(atomic_ms, 3),
        "vs_baseline": round(direct_ms / atomic_ms, 3) if atomic_ms else None,
    }
    ck_store.write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    _history_append("faults", artifact, config={"reps": reps, "instance": "burma14"})
    import shutil

    shutil.rmtree(workdir, ignore_errors=True)
    return 0


def bench_compile_child() -> int:
    """One measured process of the compile bench (``TSP_BENCH=compile-child``).

    Modes (``TSP_BENCH_COMPILE_MODE``):
      setup — build the resume checkpoint the chunk children share;
      chunk — a chunk-relay process: resume the checkpoint, run ONE
              expansion dispatch, report post-import->first-dispatch wall
              (the startup the relay re-pays per chunk) + the result;
      serve — a service process: optionally precompile the shape bucket,
              submit one batch, report the first-flush latency + tours.

    Whether the process is COLD or WARM is entirely the parent's
    ``TSP_COMPILE_CACHE`` env (off vs a shared populated dir) — the child
    code is identical, so any result difference would be the cache's
    fault and is asserted away by the parent.
    """
    import time

    t0 = time.perf_counter()  # post-import: bench.py's imports are done
    mode = os.environ.get("TSP_BENCH_COMPILE_MODE", "chunk")
    instance = os.environ.get("TSP_BENCH_COMPILE_INSTANCE", "eil51")
    ck = os.environ["TSP_BENCH_COMPILE_CKPT"]
    # k sized so the checkpoint capacity satisfies the device-loop floor
    # (4*k*(n-1) <= 1<<15 at eil51) — chunk children run device_loop=True,
    # the chunked relay's actual configuration
    k = int(os.environ.get("TSP_BENCH_COMPILE_K", "64"))

    from tsp_mpi_reduction_tpu.perf import compile_cache as perf_cache
    from tsp_mpi_reduction_tpu.utils.backend import select_backend

    platform = select_backend(os.environ.get("TSP_BENCH_COMPILE_BACKEND", "auto"))
    perf_cache.enable(platform)

    if mode == "serve":
        import numpy as np

        from tsp_mpi_reduction_tpu.serve.scheduler import MicroBatchScheduler

        n = int(os.environ.get("TSP_BENCH_COMPILE_SERVE_N", "8"))
        blocks = int(os.environ.get("TSP_BENCH_COMPILE_SERVE_B", "16"))
        rng = np.random.default_rng(7)
        xy = rng.random((blocks, n, 2)) * 1000.0
        diff = xy[:, :, None, :] - xy[:, None, :, :]
        dists = np.sqrt(np.sum(diff * diff, axis=-1))
        with MicroBatchScheduler(max_batch=blocks, max_wait_ms=1.0) as sched:
            warm_s = 0.0
            if os.environ.get("TSP_BENCH_COMPILE_WARMUP") == "1":
                t_w = time.perf_counter()
                sched.precompile([n])
                warm_s = time.perf_counter() - t_w
            t_f = time.perf_counter()
            costs, tours = sched.submit(dists).wait(timeout=600.0)
            flush_s = time.perf_counter() - t_f
        print(json.dumps({
            "mode": mode,
            "startup_s": round(time.perf_counter() - t0, 3),
            "precompile_s": round(warm_s, 3),
            "first_flush_s": round(flush_s, 3),
            "costs": [float(c) for c in costs],
            "tours": [[int(c) for c in t] for t in tours],
            "compile_cache": perf_cache.stats_dict(),
        }))
        return 0

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    d = tsplib.resolve_instance(instance).distance_matrix()
    if mode == "setup":
        # leave an unproven mid-search checkpoint for the chunk children
        res = bb.solve(d, capacity=1 << 15, k=k, max_iters=64, ils_rounds=0,
                       checkpoint_path=ck, device_loop=False)
        assert not res.proven_optimal, "setup proved early; shrink max_iters"
        print(json.dumps({"mode": mode, "cost": res.cost}))
        return 0

    # chunk mode: the relay's per-process startup — resume + ONE dispatch
    # through the transfer-free device loop (what bnb_chunked.py runs)
    res = bb.solve(d, k=k, max_iters=1, resume_from=ck, device_loop=True)
    startup_s = time.perf_counter() - t0
    print(json.dumps({
        "mode": mode,
        "startup_s": round(startup_s, 3),
        "setup_s": round(res.setup_seconds, 3),
        "dispatch_s": round(res.wall_seconds, 3),
        "cost": res.cost,
        "lb_certified": res.lower_bound,
        "compile_cache": perf_cache.stats_dict(),
    }))
    return 0


def bench_compile() -> int:
    """``TSP_BENCH=compile``: cold vs warm compile-once measurements ->
    ``BENCH_COMPILE_CACHE.json``.

    Two legs, each measured in fresh subprocesses so "process startup"
    means exactly what the chunk relay pays:

    - **chunk**: a checkpoint-resume process (the ``bnb_chunked.py``
      shape) run cold (``TSP_COMPILE_CACHE=off`` — the pre-PR behavior),
      then twice against one shared cache dir (populate, then the
      measured WARM start). Warm must be >= 3x faster post-import to
      first expansion dispatch, with identical cost/certified-LB.
    - **serve**: first-flush latency of a fresh scheduler process, cold
      vs warmed (precompile + populated cache), tours bit-identical.
    """
    import shutil
    import subprocess
    import tempfile

    workdir = tempfile.mkdtemp(prefix="bench_compile_")
    cache_dir = os.path.join(workdir, "compile_cache")
    ck = os.path.join(workdir, "seed.npz")
    out_path = os.environ.get("TSP_BENCH_COMPILE_OUT", "BENCH_COMPILE_CACHE.json")
    backend = os.environ.get("TSP_BENCH_COMPILE_BACKEND", "auto")

    def run_child(mode: str, cache: str, warmup: bool = False) -> dict:
        env = dict(
            os.environ,
            TSP_BENCH="compile-child",
            TSP_BENCH_COMPILE_MODE=mode,
            TSP_BENCH_COMPILE_CKPT=ck,
            TSP_BENCH_COMPILE_BACKEND=backend,
            TSP_COMPILE_CACHE=cache,
        )
        if warmup:
            env["TSP_BENCH_COMPILE_WARMUP"] = "1"
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=1800, env=env,
        )
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-2000:])
            raise RuntimeError(f"compile-bench child {mode} rc={r.returncode}")
        os.environ["TSP_BACKEND_PROBED"] = "1"  # children share one probe
        return json.loads(r.stdout.strip().splitlines()[-1])

    try:
        run_child("setup", "off")
        cold = run_child("chunk", "off")
        populate = run_child("chunk", cache_dir)
        warm = run_child("chunk", cache_dir)

        serve_cold = run_child("serve", "off")
        run_child("serve", cache_dir, warmup=True)  # populate serve entries
        serve_warm = run_child("serve", cache_dir, warmup=True)
    except BaseException:
        # a failed child must not leak the workdir (seed checkpoint + a
        # populated executable cache — can be hundreds of MB in /tmp)
        shutil.rmtree(workdir, ignore_errors=True)
        raise

    speedup = cold["startup_s"] / warm["startup_s"] if warm["startup_s"] else None
    artifact = {
        "metric": "compile_once_warm_start",
        "unit": "x cold/warm chunk startup",
        "value": round(speedup, 2) if speedup else None,
        "instance": os.environ.get("TSP_BENCH_COMPILE_INSTANCE", "eil51"),
        "backend": backend,
        "chunk": {
            "cold_startup_s": cold["startup_s"],
            "populate_startup_s": populate["startup_s"],
            "warm_startup_s": warm["startup_s"],
            "speedup": round(speedup, 2) if speedup else None,
            "costs_equal": cold["cost"] == warm["cost"] == populate["cost"],
            "lb_equal": cold["lb_certified"] == warm["lb_certified"],
            "cost": cold["cost"],
            "lb_certified": cold["lb_certified"],
            "warm_compile_cache": warm["compile_cache"],
        },
        "serve": {
            "cold_first_flush_s": serve_cold["first_flush_s"],
            "warm_first_flush_s": serve_warm["first_flush_s"],
            "warm_precompile_s": serve_warm["precompile_s"],
            "flush_speedup": round(
                serve_cold["first_flush_s"] / serve_warm["first_flush_s"], 2
            ) if serve_warm["first_flush_s"] else None,
            "tours_match": serve_cold["tours"] == serve_warm["tours"]
            and serve_cold["costs"] == serve_warm["costs"],
        },
    }
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    _history_append("compile", artifact, config={
        "instance": artifact["instance"], "backend": backend,
    })
    shutil.rmtree(workdir, ignore_errors=True)
    ok = (
        artifact["chunk"]["costs_equal"]
        and artifact["chunk"]["lb_equal"]
        and artifact["serve"]["tours_match"]
    )
    return 0 if ok else 1


def bench_bnb() -> int:
    """North-star metric: B&B nodes/sec to proven optimality (default
    instance eil51 — see module docstring for why not berlin52)."""
    import jax

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)
    name = os.environ.get("TSP_BENCH_INSTANCE", "eil51")
    inst = tsplib.embedded(name)
    d = inst.distance_matrix()
    n = d.shape[0]
    k = int(os.environ.get("TSP_BENCH_K", "1024"))
    capacity = max(1 << 17, 8 * k * (n - 1))
    # per-node mini-ascent depth: more steps = fewer nodes but more Prims
    # per pop; the best time-to-proof point is hardware-dependent
    na = int(os.environ.get("TSP_BENCH_NODE_ASCENT", "2"))
    # MST bound kernel: prim (sequential jnp chain), boruvka (log-depth
    # batched rounds — recorded negative result), or prim_pallas (the
    # whole chain fused into one Pallas kernel — 0.74 vs 2.92 ms per
    # bound eval on a v5e). Default: prim_pallas on TPU backends for
    # n <= 128 (the COMPILED kernel's lane limit — 256 lanes are
    # interpret-only, prim_pallas.py docstring), falling back to prim for
    # larger instances and everywhere off-TPU (interpret mode would be
    # slower than jnp on CPU).
    on_cpu = jax.default_backend() == "cpu"
    on_tpu = jax.default_backend() == "tpu"
    mk = os.environ.get(
        "TSP_BENCH_MST_KERNEL",
        "prim_pallas" if (on_tpu and n <= 128) else "prim",
    )
    # push ordering: "best-first" (default) or "natural" (skip the
    # per-step two-level sort: cheaper steps, possibly more nodes — on
    # eil51 the ILS start is not optimal, so pop order does shape the
    # tree; BENCH_BNB_TPU_R5_NOSORT.json is the on-chip A/B verdict)
    po = os.environ.get("TSP_BENCH_PUSH_ORDER", "best-first")
    # capped push-block rows (0 = full k*n; scatter_profile v4 sizes it)
    pb = int(os.environ.get("TSP_BENCH_PUSH_BLOCK", "0"))
    if mk not in bb._MST_CONN:
        print(
            f"bench: TSP_BENCH_MST_KERNEL={mk!r} is not one of "
            f"{sorted(bb._MST_CONN)}", file=sys.stderr,
        )
        return 2

    t0 = time.perf_counter()
    if on_cpu:
        # no relay, no poison: a tiny warmup run compiles the host-loop
        # kernels; the fine-grained host loop also honors time_limit_s
        bb.solve(d, capacity=capacity, k=k, node_ascent=na,
                 device_loop=False, max_iters=8, mst_kernel=mk,
                 push_order=po, push_block=pb)
    else:
        # AOT compile only (no device execution -> the relay stays in fast
        # mode); integral must match what _bound_setup will derive from
        # the data or the timed dispatch recompiles a new static config
        bb.warm_compile_device_solver(
            n, capacity, k, bb._is_integral(d), True, na, mst_kernel=mk,
            push_order=po, push_block=pb,
        )
    print(f"warmup (compile): {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    res = bb.solve(
        d, capacity=capacity, k=k, time_limit_s=600, node_ascent=na,
        device_loop=not on_cpu, max_iters=5_000_000, mst_kernel=mk,
        push_order=po, push_block=pb,
    )
    ok = res.proven_optimal and res.cost == inst.known_optimum
    print(
        f"{name}: cost={res.cost} (known {inst.known_optimum}) "
        f"proven={res.proven_optimal} nodes={res.nodes_expanded} "
        f"wall={res.wall_seconds:.2f}s setup={res.setup_seconds:.1f}s "
        f"(ascent {res.ascent_seconds:.1f} + ils {res.ils_seconds:.1f} + "
        f"backend {res.setup_seconds - res.ascent_seconds - res.ils_seconds:.1f})",
        file=sys.stderr,
    )
    if not ok:
        print("bench: WARNING — run did not prove the known optimum", file=sys.stderr)
    value = res.nodes_per_sec
    from tsp_mpi_reduction_tpu.obs import costs as obs_costs

    artifact = {
        "metric": f"bnb_{name}_nodes_per_sec",
        "value": round(value, 1),
        "unit": "nodes/s",
        "vs_baseline": round(value / BNB_CPU_8RANK_ANCHOR, 2),
        "proven_optimal": bool(res.proven_optimal),
        "device": "cpu" if on_cpu else str(dev),
        # time-to-proof is the robust cross-engine number
        # (nodes/sec across engines with different bounds is
        # apples-to-oranges); anchor caveat made explicit. None
        # when the run stopped without a proof — a finite value
        # must never describe a proof that didn't happen
        "time_to_proof_s": (
            round(res.setup_seconds + res.wall_seconds, 2)
            if res.proven_optimal
            else None
        ),
        "setup_s": round(res.setup_seconds, 2),
        "setup_ascent_s": round(res.ascent_seconds, 2),
        "setup_ils_s": round(res.ils_seconds, 2),
        "mst_kernel": mk,
        "push_order": po,
        "push_block": pb,
        "anchor": (
            "this engine's own 1-rank CPU rate x8 "
            "(assumes perfect 8-way MPI scaling)"
        ),
        # XLA cost attribution for the hot entries this run compiled
        # (flops/bytes/roofline estimate; empty when the compile cache
        # was disabled — capture rides its custody of the executables)
        "obs": {"device_costs": obs_costs.device_costs_block()},
    }
    print(json.dumps(artifact))
    _history_append("bnb", artifact, config={
        "instance": name, "k": k, "capacity": capacity, "node_ascent": na,
        "mst_kernel": mk, "push_order": po, "push_block": pb,
        "device_loop": not on_cpu,
    })
    return 0


def bench_spill() -> int:
    """Reservoir transfer accounting (PR 2 acceptance): an 8-virtual-device
    CPU mesh with a tiny per-rank capacity forces constant spill traffic;
    the JSON reports measured bytes per spill round vs the pre-PR-2
    full-buffer round trip on the same run. CPU-only BY DESIGN — the
    counters measure bytes moved, which is backend-independent."""
    from tsp_mpi_reduction_tpu.utils.backend import force_host_platform

    ranks = int(os.environ.get("TSP_BENCH_SPILL_RANKS", "8"))
    force_host_platform(ranks)

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh

    # defaults sized so every rank spills continuously (capacity barely
    # above the per-step growth bound k*(n-1)); larger capacities shrink
    # the event count toward zero on this small instance
    n = int(os.environ.get("TSP_BENCH_SPILL_N", "14"))
    cap = int(os.environ.get("TSP_BENCH_SPILL_CAPACITY", "96"))
    k = 4
    rng = np.random.default_rng(51)
    xy = rng.uniform(0, 100, (n, 2))
    d = np.rint(np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1)) * 10)
    # min-out + no MST pruning maximizes frontier pressure (the reservoir
    # regression-test config): every rank spills continuously
    res = bb.solve_sharded(
        d, make_rank_mesh(ranks), capacity_per_rank=cap, k=k, inner_steps=1,
        bound="min-out", mst_prune=False, node_ascent=0, max_iters=2_000_000,
    )
    width = n + (n + 31) // 32 + 4
    phys_rows = cap + k * n  # logical capacity + k*n push-padding rows
    # HEAD moved the WHOLE stacked physical buffer down and back up on
    # every spill round (np.asarray(fr.nodes).copy() + device_put)
    head_per_round = 2 * ranks * phys_rows * width * 4
    print(
        f"spill bench: proven={res.proven_optimal} rounds={res.spill_rounds} "
        f"events={res.spill_events} full_merges={res.spill_full_merges}",
        file=sys.stderr,
    )
    if res.spill_rounds == 0:
        # a config that never spills measures nothing — say so instead of
        # reporting a 0-bytes/round "measurement" with an absurd ratio
        print(json.dumps({
            "metric": "sharded_spill_transfer_bytes_per_round",
            "value": None,
            "unit": "bytes",
            "error": (
                "no spill rounds occurred at this config — lower "
                "TSP_BENCH_SPILL_CAPACITY or raise TSP_BENCH_SPILL_N"
            ),
            "ranks": ranks, "n": n, "capacity_per_rank": cap,
        }))
        return 1
    measured = (
        res.spill_bytes_to_host + res.spill_bytes_to_device
    ) / res.spill_rounds
    artifact = {
        "metric": "sharded_spill_transfer_bytes_per_round",
        "value": round(measured, 1),
        "unit": "bytes",
        # improvement factor vs HEAD's full-buffer round trip
        "vs_baseline": round(head_per_round / max(measured, 1.0), 2),
        "head_equiv_bytes_per_round": head_per_round,
        "spill_rounds": res.spill_rounds,
        "spill_events": res.spill_events,
        "spill_full_merges": res.spill_full_merges,
        "spill_bytes_to_host": res.spill_bytes_to_host,
        "spill_bytes_to_device": res.spill_bytes_to_device,
        "proven_optimal": bool(res.proven_optimal),
        "ranks": ranks,
        "n": n,
        "capacity_per_rank": cap,
        "anchor": (
            "pre-PR-2 spill_refill: full stacked buffer "
            "(capacity + k*n padding rows, all ranks) transferred "
            "host-ward and back per spill round"
        ),
    }
    print(json.dumps(artifact))
    _history_append("spill", artifact, config={
        "ranks": ranks, "n": n, "capacity_per_rank": cap,
    })
    return 0


def bench_step_child() -> int:
    """One measured process of the step bench (``TSP_BENCH=step-child``):
    chained transfer-free ``_expand_loop_ref`` dispatches of the real
    expansion step under ONE step kernel (TSP_BENCH_STEP_KERNEL), one
    readback at the end — the same method as tools/step_profile.py.
    Prints one JSON line: ms/step, nodes popped, final incumbent (the
    cross-kernel exactness check)."""
    from tsp_mpi_reduction_tpu.utils.backend import (
        enable_persistent_cache,
        select_backend,
    )

    platform = select_backend(os.environ.get("TSP_BENCH_BACKEND", "auto"))
    enable_persistent_cache(platform)

    import jax
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    kernel = os.environ.get("TSP_BENCH_STEP_KERNEL", "reference")
    inst = tsplib.embedded(os.environ.get("TSP_BENCH_STEP_INSTANCE", "eil51"))
    d = inst.distance_matrix()
    n = d.shape[0]
    k = int(os.environ.get("TSP_BENCH_STEP_K", "1024"))
    steps = int(os.environ.get("TSP_BENCH_STEP_STEPS", "8"))
    dispatches = int(os.environ.get("TSP_BENCH_STEP_DISPATCHES", "6"))
    warm = int(os.environ.get("TSP_BENCH_STEP_WARM", "10"))
    # MST re-bound off by default: the step kernels differ ONLY in the
    # pop/sort/push data movement, so the A/B isolates exactly that
    use_mst = os.environ.get("TSP_BENCH_STEP_MST", "0") == "1"
    # capacity: the step-profile sizing, CAPPED so the fused leg's
    # physical buffer (capacity + k*n padding rows) fits the compiled
    # Pallas VMEM budget — otherwise the TPU fused leg would refuse at
    # trace time and the acceptance artifact could never be captured.
    # Both legs share the capacity so the A/B stays apples-to-apples.
    from tsp_mpi_reduction_tpu.ops.expand_pallas import VMEM_BUDGET_BYTES

    cols = bb._path_words(n) + (n + 31) // 32 + 4
    fit_rows = VMEM_BUDGET_BYTES // (cols * 4) - k * n
    capacity = int(os.environ.get(
        "TSP_BENCH_STEP_CAPACITY",
        min(max(1 << 17, 8 * k * (n - 1)), max(fit_rows, 4 * k * n)),
    ))

    bd = bb._bound_setup(d, "one-tree", node_ascent=0, ascent="host")
    d64 = np.asarray(d, np.float64)
    tour = bb.nearest_neighbor_tour(d64)
    inc_cost = jnp.asarray(bb.tour_cost(d64, tour), jnp.float32)
    inc_tour = jnp.asarray(tour, jnp.int32)
    fr = bb.make_root_frontier(n, capacity, np.asarray(bd.min_out, np.float64))
    d32 = jnp.asarray(d, jnp.float32)
    args = (d32, bd.min_out, bd.bound_adj, bd.dbar, bd.pi, bd.slack,
            bd.ascent_step, bd.lam_budget)

    # warm to a realistic mid-search stack (reference kernel: both
    # children must start from the IDENTICAL warm state)
    fr, inc_cost, inc_tour, _ = bb._expand_loop_ref(
        fr, inc_cost, inc_tour, *args, k, n, warm, bd.integral, True, 0,
        "prim", "best-first", 0, "reference",
    )

    def dispatch(carry):
        _, ic2, _, nodes = bb._expand_loop_ref(
            fr, carry, inc_tour, *args, k, n, steps, bd.integral, use_mst,
            0, "prim", "best-first", 0, kernel,
        )
        return ic2, nodes

    t0 = time.perf_counter()
    c, nodes = dispatch(inc_cost * 1.0)
    jax.block_until_ready(c)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(dispatches):
        c, nodes = dispatch(c)
    final = float(c)  # the ONE readback drains the chain
    wall = time.perf_counter() - t0
    print(json.dumps({
        "step_kernel": kernel,
        "ms_per_step": round(wall * 1000.0 / (dispatches * steps), 4),
        "nodes_per_step": int(nodes) // max(steps, 1),
        "nodes_per_sec": round(
            int(nodes) * dispatches / max(wall, 1e-9), 1
        ),
        "final_incumbent": final,
        "use_mst": use_mst,
        "k": k, "n": n, "steps": steps, "dispatches": dispatches,
        "compile_s": round(compile_s, 2),
        "row_bytes": int(fr.nodes.shape[-1]) * 4,
        "backend": platform,
    }))
    return 0


def bench_step() -> int:
    """``TSP_BENCH=step`` (ISSUE 8 acceptance): fused vs reference
    expansion-step cost, each kernel measured in a FRESH subprocess
    (compile caches and relay state cannot leak between legs), plus the
    packed-row spill-bytes ratio vs the v1 unpacked layout. Writes
    ``BENCH_STEP_FUSED.json`` (path: TSP_BENCH_STEP_OUT).

    On TPU the fused kernel is the compiled Pallas path and the target
    is >= 2x on the scatter+sort portion; on CPU the fused kernel runs
    in INTERPRET mode (a correctness vehicle, not a speed claim) — the
    artifact records both legs honestly with the backend label."""
    import subprocess

    out_path = os.environ.get("TSP_BENCH_STEP_OUT", "BENCH_STEP_FUSED.json")
    legs = {}
    for kernel in ("reference", "fused"):
        env = dict(
            os.environ, TSP_BENCH="step-child", TSP_BENCH_STEP_KERNEL=kernel
        )
        r = subprocess.run(
            [sys.executable, __file__], capture_output=True, text=True,
            env=env, timeout=1800,
        )
        sys.stderr.write(r.stderr[-2000:])
        try:
            legs[kernel] = json.loads(r.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            print(f"step bench: {kernel} leg produced no JSON "
                  f"(rc={r.returncode})", file=sys.stderr)
            return 1
    ref, fus = legs["reference"], legs["fused"]
    n = int(ref["n"])
    v1_row_bytes = (n + (n + 31) // 32 + 4) * 4
    artifact = {
        "metric": "fused_vs_reference_expansion_step",
        # headline value for the history gate: fused speedup vs reference
        "value": round(ref["ms_per_step"] / max(fus["ms_per_step"], 1e-9), 3),
        "unit": "x",
        "reference": ref,
        "fused": fus,
        "speedup_fused_vs_reference": round(
            ref["ms_per_step"] / max(fus["ms_per_step"], 1e-9), 3
        ),
        # the two kernels share every screen/ordering computation — the
        # chained runs must converge to the SAME incumbent
        "incumbent_match": ref["final_incumbent"] == fus["final_incumbent"],
        "row_bytes_packed": ref["row_bytes"],
        "row_bytes_v1_unpacked": v1_row_bytes,
        "row_bytes_ratio": round(v1_row_bytes / ref["row_bytes"], 2),
        "backend": ref["backend"],
        "fused_mode": (
            "compiled" if ref["backend"] == "tpu" else "interpret"
        ),
        "method": (
            "chained transfer-free _expand_loop_ref dispatches, one "
            "readback per fresh subprocess (tools/step_profile.py method)"
        ),
    }
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    _history_append("step", artifact, config={
        "n": n, "backend": ref["backend"], "fused_mode": artifact["fused_mode"],
    })
    if not artifact["incumbent_match"]:
        return 1
    return 0


def bench_serve() -> int:
    """Serving-layer acceptance bench (ISSUE 3): micro-batched vs
    sequential single-instance throughput on a same-shape workload, cache
    hit rate on permuted/translated resubmission, and deadline-ladder
    behavior under an impossible budget. Emits ``BENCH_SERVE.json``
    (path: ``TSP_BENCH_SERVE_OUT``) AND prints the same one-line JSON.

    Default workload: 48 unique n=8 instances. n is deliberately small on
    CPU — XLA CPU runs vmap lanes serially, so batching pays off through
    dispatch amortization, which dominates at small n (measured 5.2x at
    n=8 vs 1.4x at n=12 on this host); on TPU the lanes are data-parallel
    and the win grows with n instead.

    The headline ratio compares device-call granularities on the same
    workload: the repo's status quo ante — one ``solve_blocks_from_dists``
    dispatch + readback per instance, exactly what every pre-serve entry
    point does — against the scheduler's micro-batched path (all requests
    submitted as tickets, flushed as one padded vmap call). Both run the
    identical kernel, so tours must be bit-identical.

    The ``service_ratio`` legs (ISSUE 13) run the MIXED workload through
    the full service: one long certified B&B proof arrives at the head of
    the line, then the 48 latency-sensitive HK requests. Request-level
    scheduling (the pre-ISSUE-13 posture: one request at a time, every
    job runs to completion) makes the short requests wait out the whole
    proof; the iteration-level loop preempts the proof at each
    ``bnb_slice_s`` boundary via the donated-checkpoint path and serves
    the HK batch in the gaps. ``service_ratio`` is the short-request
    completion-throughput ratio between the two, the proof itself must
    finish PROVEN and bit-identical in both legs, and the preemptions /
    resumes are asserted in the stats JSON and the span tree. The
    tight-deadline leg then re-checks tier routing: feasible-but-tight
    budgets must be answered by an exact rung (the learned-EWMA path),
    impossible budgets still degrade to a valid greedy tour."""
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix_np
    from tsp_mpi_reduction_tpu.ops.held_karp import solve_blocks_from_dists
    from tsp_mpi_reduction_tpu.serve import (
        LadderConfig,
        MicroBatchScheduler,
        ServiceConfig,
        SolveService,
    )

    n = int(os.environ.get("TSP_BENCH_SERVE_N", "8"))
    reqs_total = int(os.environ.get("TSP_BENCH_SERVE_REQS", "48"))
    out_path = os.environ.get("TSP_BENCH_SERVE_OUT", "BENCH_SERVE.json")
    rng = np.random.default_rng(7)
    instances = [rng.uniform(0, 1000, (n, 2)) for _ in range(reqs_total)]
    dists = [distance_matrix_np(xy) for xy in instances]
    requests = [
        # sub-second deadlines keep the HK cohort on the exact pipeline
        # rung (below bnb_min_budget_s) while leaving ample slack, so
        # both service legs time the SAME compute and the ratio isolates
        # the scheduling, not tier luck
        {"id": i, "xy": inst.tolist(), "deadline_ms": 900.0}
        for i, inst in enumerate(instances)
    ]
    # serving-sized B&B knobs; bnb_slice_s is the preemption granularity
    # the continuous-batching legs exercise
    ladder_cfg = LadderConfig(
        bnb_max_n=40, bnb_capacity=4096, bnb_k=32, bnb_slice_s=0.05
    )
    # the head-of-line proof of the mixed workload: big enough that the
    # certified search genuinely runs multi-slice (~2s uninterrupted on
    # this host, ~40 preemption boundaries), small enough to prove
    bnb_n = int(os.environ.get("TSP_BENCH_SERVE_BNB_N", "38"))
    bnb_xy = np.random.default_rng(3).uniform(0, 1000, (bnb_n, 2))
    bnb_req = {"id": "proof", "xy": bnb_xy.tolist(), "deadline_ms": 30_000.0}

    # warm the XLA cache for both batch shapes OUTSIDE the timed windows
    # (compile is a one-time cost with the persistent cache; the reference
    # baseline has no JIT)
    t0 = time.perf_counter()
    warm = np.stack(dists)
    # two-shape compile warmup, not a hot loop  # graftlint: disable=R4
    for shape in (warm[:1], warm):
        c, _ = solve_blocks_from_dists(jnp.asarray(shape, jnp.float32), jnp.float32)
        np.asarray(c)
    # warm the certified rung's kernels AND the in-process ascent memo
    # for the proof instance (one-time costs either leg would otherwise
    # pay asymmetrically inside its timed window)
    from tsp_mpi_reduction_tpu.models import branch_bound as bb

    bb.solve(
        distance_matrix_np(bnb_xy), time_limit_s=0.05,
        capacity=ladder_cfg.bnb_capacity, k=ladder_cfg.bnb_k,
        device_loop=False,
    )
    print(f"serve bench warmup (compile): {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # -- headline leg A: sequential single-instance solves (status quo:
    # one dispatch + readback per instance, as utils/cli.py does today)
    t0 = time.perf_counter()
    seq_tours = []
    # the MEASURED BASELINE is per-instance dispatch  # graftlint: disable=R4
    for d in dists:
        _, tours = solve_blocks_from_dists(jnp.asarray(d[None], jnp.float32), jnp.float32)
        seq_tours.append(np.asarray(tours)[0])
    seq_wall = time.perf_counter() - t0
    seq_rps = reqs_total / seq_wall

    # -- headline leg B: the micro-batched path — same instances as
    # scheduler tickets, flushed as ONE padded vmap device call
    with MicroBatchScheduler(
        max_batch=reqs_total, max_wait_ms=20.0
    ) as sched:
        t0 = time.perf_counter()
        tickets = [sched.submit(d[None]) for d in dists]
        bat_tours = [t.wait(timeout=120.0)[1][0] for t in tickets]
        bat_wall = time.perf_counter() - t0
        sched_stats = sched.stats()
    bat_rps = reqs_total / bat_wall
    tours_match = all(
        np.array_equal(s, b) for s, b in zip(seq_tours, bat_tours)
    )

    # -- mixed-workload service legs (ISSUE 13): the head-of-line proof
    # plus the 48 HK requests through the FULL request path. Leg 1 is the
    # request-level posture — one request at a time, every job runs to
    # completion, so the short requests wait out the whole proof. Leg 2
    # is the iteration-level loop: the proof is preempted at each slice
    # boundary and the HK batch is admitted into the gaps. The governed
    # figure is the SHORT-request completion throughput ratio.
    seq_cfg = ServiceConfig(
        max_batch=1, max_wait_ms=0.0, threads=1, ladder=ladder_cfg
    )
    svc_seq_responses = {}
    with SolveService(seq_cfg) as svc_seq:
        t0 = time.perf_counter()
        seq_bnb_resp = svc_seq.handle(bnb_req)
        for req in requests:
            resp = svc_seq.handle(req)
            svc_seq_responses[resp["id"]] = resp
        seq_service_wall = time.perf_counter() - t0
    seq_service_rps = reqs_total / seq_service_wall

    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from tsp_mpi_reduction_tpu.obs import tracing as _serve_tracing

    trace_dir = tempfile.mkdtemp(prefix="bench_serve_")
    trace_path = os.path.join(trace_dir, "serve_trace.jsonl")
    _serve_tracing.configure(trace_path)
    bat_cfg = ServiceConfig(
        max_batch=reqs_total, max_wait_ms=20.0, threads=reqs_total,
        ladder=ladder_cfg,
    )
    svc = SolveService(bat_cfg)
    with ThreadPoolExecutor(max_workers=reqs_total + 1) as pool:
        # spin the pool's threads up outside the timed window
        list(pool.map(lambda _: None, range(reqs_total + 1)))
        t0 = time.perf_counter()
        bnb_future = pool.submit(svc.handle, bnb_req)
        futures = [pool.submit(svc.handle, r) for r in requests]
        bat_responses = {}
        for f in futures:
            r = f.result(timeout=120.0)
            bat_responses[r["id"]] = r
        bat_service_wall = time.perf_counter() - t0
        bat_bnb_resp = bnb_future.result(timeout=120.0)
        bat_total_wall = time.perf_counter() - t0
    bat_service_rps = reqs_total / bat_service_wall

    service_tours_match = all(
        svc_seq_responses[i]["tour"] == bat_responses[i]["tour"]
        and list(bat_responses[i]["tour"][:-1]) == list(map(int, seq_tours[i][:-1]))
        for i in range(reqs_total)
    )
    # the preempted/resumed proof must land where the uninterrupted
    # search lands: proven optimal, same cost, same tour — bit-identical
    # through however many donated-checkpoint round-trips each leg took
    bnb_identical = (
        seq_bnb_resp["tier"] == "bnb"
        and bat_bnb_resp["tier"] == "bnb"
        and seq_bnb_resp["certified_gap"] == 0.0
        and bat_bnb_resp["certified_gap"] == 0.0
        and seq_bnb_resp["cost"] == bat_bnb_resp["cost"]
        and seq_bnb_resp["tour"] == bat_bnb_resp["tour"]
    )

    # -- leg 3: resubmit every instance permuted + translated -> 100% hits
    hits_before = svc.cache.stats()["hits"]
    resub_ok = 0
    for i, inst in enumerate(instances):
        shuffled = inst[rng.permutation(n)] + rng.integers(-500, 500)
        resp = svc.handle(
            {"id": f"dup{i}", "xy": shuffled.tolist(), "deadline_ms": 900.0}
        )
        if resp.get("cache") == "hit":
            resub_ok += 1
    hit_rate = (svc.cache.stats()["hits"] - hits_before) / reqs_total

    # -- leg 4: deadline-tier routing. The tight cohort carries a
    # feasible-but-tight budget: far below the bnb admission floor, yet
    # answerable by the exact micro-batched rung once the EWMA has
    # learned its real latency (pre-ISSUE-13 these degraded to greedy).
    # The impossible cohort keeps the old guarantee: ANY deadline still
    # gets a valid closed tour.
    tight_reqs, impossible_reqs = 24, 8
    deadline_reqs = tight_reqs + impossible_reqs
    deadline_valid = 0
    tight_exact = 0
    deadline_tiers = {}
    for i in range(deadline_reqs):
        xy = rng.uniform(0, 1000, (n, 2))
        tight = i < tight_reqs
        resp = svc.handle(
            {
                "id": f"dl{i}",
                "xy": xy.tolist(),
                "deadline_ms": 350.0 if tight else 0.001,
            }
        )
        tour = resp.get("tour", [])
        if (
            "error" not in resp
            and tour
            and tour[0] == tour[-1]
            and sorted(tour[:-1]) == list(range(n))
        ):
            deadline_valid += 1
        if (
            tight
            and resp.get("tier") in ("bnb", "pipeline")
            and resp.get("certified_gap") == 0.0
        ):
            tight_exact += 1
        deadline_tiers[resp.get("tier", "error")] = (
            deadline_tiers.get(resp.get("tier", "error"), 0) + 1
        )
    tight_exact_rate = tight_exact / tight_reqs
    stats = json.loads(svc.stats_json())
    svc.close()
    _serve_tracing.configure(None)

    # preemption evidence from the span tree: the scheduler emits one
    # ``bnb.slice`` span per device slice, attributed preempted/resumed
    spans = _serve_tracing.read_trace(trace_path)
    slice_spans = [s for s in spans if s.get("name") == "bnb.slice"]
    preempt_spans = sum(
        1 for s in slice_spans if s.get("attrs", {}).get("preempted")
    )
    resume_spans = sum(
        1 for s in slice_spans if s.get("attrs", {}).get("resumed")
    )
    import shutil

    shutil.rmtree(trace_dir, ignore_errors=True)

    admission = stats.get("admission", {})
    ratio = bat_rps / seq_rps
    service_ratio = bat_service_rps / seq_service_rps
    ok = (
        tours_match
        and service_tours_match
        and bnb_identical
        and ratio >= 2.0
        and service_ratio >= 3.0
        and hit_rate >= 1.0
        and deadline_valid == deadline_reqs
        and tight_exact_rate >= 0.9
        and int(admission.get("preemptions", 0)) >= 1
        and int(admission.get("resumes", 0)) >= 1
        and preempt_spans >= 1
        and resume_spans >= 1
    )
    artifact = {
        "metric": "serve_microbatch_vs_sequential_throughput",
        "value": round(ratio, 2),
        "unit": "x",
        "sequential_rps": round(seq_rps, 1),
        "batched_rps": round(bat_rps, 1),
        # mixed-workload legs: HK-cohort completion throughput with the
        # head-of-line proof run-to-completion (sequential) vs preempted
        # into slices (batched) — the ISSUE 13 governed ratio
        "sequential_service_rps": round(seq_service_rps, 1),
        "batched_service_rps": round(bat_service_rps, 1),
        "service_ratio": round(service_ratio, 2),
        "requests": reqs_total,
        "n": n,
        "bnb_n": bnb_n,
        "bnb_identical": bool(bnb_identical),
        "bnb_cost": float(bat_bnb_resp["cost"]),
        "bnb_wall_batched_s": round(bat_total_wall, 3),
        "tours_match": bool(tours_match),
        "service_tours_match": bool(service_tours_match),
        "cache_hit_rate_resubmit": round(hit_rate, 3),
        "deadline_requests": deadline_reqs,
        "deadline_valid_responses": deadline_valid,
        "deadline_misses": stats["deadline_misses"],
        "deadline_tiers": deadline_tiers,
        "tight_deadline_requests": tight_reqs,
        "tight_deadline_exact_rate": round(tight_exact_rate, 3),
        "preempt_spans": preempt_spans,
        "resume_spans": resume_spans,
        "admission": admission,
        "microbatch_scheduler": sched_stats,
        "service_scheduler": stats["scheduler"],
        "cache": stats["cache"],
        "tiers": stats["tiers"],
        "device": str(__import__("jax").devices()[0]),
        "ok": bool(ok),
    }
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    hist_cfg = {"requests": reqs_total, "n": n, "bnb_n": bnb_n}
    _history_append("serve", artifact, config=hist_cfg)
    # governed series two and three (ISSUE 13): the mixed-workload
    # continuous-batching ratio and the tight-deadline exact-answer rate
    _history_append("serve", {
        "metric": "serve_service_ratio",
        "value": round(service_ratio, 2),
        "unit": "x",
        "ok": bool(ok),
    }, config=hist_cfg)
    _history_append("serve", {
        "metric": "serve_tight_deadline_exact_rate",
        "value": round(tight_exact_rate, 3),
        "unit": "rate",
        "ok": bool(ok),
    }, config=hist_cfg)
    return 0 if ok else 1


def bench_obs() -> int:
    """Telemetry overhead + trace completeness (ISSUE 6/9 acceptance).

    Two legs, both forced-CPU (host-side instrumentation is what is being
    priced, not the accelerator):

    1. **B&B telemetry cost** — the same solve config run with full
       telemetry (metrics + span tracing to a real JSONL sink + the
       per-dispatch sampler + stall sentinel) vs ``TSP_OBS=off``, in
       back-to-back order-alternating pairs. The GATED figure
       (``overhead_pct`` <= 2%) is the metered one: every obs entry
       point the solve crosses (``StepSampler.sample`` — which forwards
       the sentinel feed — the series/summary flushes, every trace-sink
       write) runs under a ``perf_counter`` accumulator, and the
       overhead is that serial obs time over the solve's remaining
       wall. The A/B wall ratio is still computed and reported
       (``wall_ratio_pct``) but NOT gated: measured back-to-back pair
       ratios of the bit-identical solve swing 0.66x-1.31x on a
       contended CI host, so a wall gate at 2% would be reading
       scheduler noise, not telemetry cost (the metered figure is also
       what the ``obs_us_per_dispatch`` history series tracks — stable
       to fractions of a us against hook-cost creep).
    2. **serve trace** — a multi-request JSONL session (including a
       malformed line and an impossible deadline) traced to JSONL; every
       parsed request must reconstruct into a complete span tree (no
       orphan spans) rooted at ``serve.request``.

    Emits ``BENCH_OBS.json`` (path: ``TSP_BENCH_OBS_OUT``) and prints the
    same one-line JSON. Exit 1 when either acceptance criterion fails."""
    import io
    import statistics
    import tempfile

    import numpy as np

    from tsp_mpi_reduction_tpu import obs
    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.obs import costs as obs_costs
    from tsp_mpi_reduction_tpu.obs import tracing
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic
    from tsp_mpi_reduction_tpu.utils import tsplib

    reps = int(os.environ.get("TSP_BENCH_OBS_REPS", "7"))
    spec = os.environ.get("TSP_BENCH_OBS_INSTANCE", "random:12:33")
    out_path = os.environ.get("TSP_BENCH_OBS_OUT", "BENCH_OBS.json")
    workdir = tempfile.mkdtemp(prefix="bench_obs_")
    inst = tsplib.resolve_instance(spec)
    d = np.rint(inst.distance_matrix() * 10)
    # host-loop-heavy config: inner_steps=4 is 8x denser host-loop
    # sampling than the engine default (32) — per-dispatch telemetry is
    # still the dominant obs cost — while keeping dispatches large
    # enough that the wall ratio prices telemetry, not the ~3 us/call
    # icache floor ANY per-dispatch Python hook pays at inner_steps=1
    # (measured: the same hook costs 3x more per call inside the live
    # loop than in a microbenchmark, purely from cache displacement).
    # The marginal per-dispatch cost is additionally tracked below as
    # its own history metric, which catches hook-cost creep with far
    # better sensitivity than any wall ratio.
    # (capacity rides with inner_steps: the in-kernel push needs
    # inner_steps * k * n rows of spill headroom to keep the proof)
    kw = dict(capacity=2048, k=8, inner_steps=4, bound="min-out",
              mst_prune=False, node_ascent=0, device_loop=False)

    # compile cache ON (a bench-local dir unless the env chose one): the
    # ISSUE 9 cost-capture path rides its custody of the executables, so
    # this bench prices telemetry + cost capture together — capture runs
    # once at the warmup compile below, and the device_costs block lands
    # in the artifact as the schema evidence
    os.environ.setdefault(
        "TSP_COMPILE_CACHE", os.path.join(workdir, "compile_cache")
    )
    from tsp_mpi_reduction_tpu.perf import compile_cache as perf_cache

    perf_cache.enable()

    bb.solve(d, **kw)  # warm the XLA compiles out of both arms

    # -- the hook meter: serial-time accumulator over every obs entry
    # point the solve crosses. The per-dispatch hook (StepSampler.sample,
    # which forwards the sentinel feed) self-times through its NATIVE
    # METER_NS — a wrapping frame would bill its own ~1.5 us/call of
    # packing cost to the thing it measures. The cold once-per-solve
    # surfaces (series flush, sentinel summary, trace-sink writes) are
    # wrapped instead, where frame cost is irrelevant. The meter stays
    # armed for BOTH arms (symmetric walls); under TSP_OBS=off the
    # sampler/sentinel do not exist and the trace sink is closed, so the
    # off arm never enters any of it. Residual meter self-cost (two
    # perf_counter_ns per dispatch) is billed TO the obs arm — the meter
    # over-, never under-counts.
    from tsp_mpi_reduction_tpu.obs import anomaly as obs_anomaly
    from tsp_mpi_reduction_tpu.obs import timeseries as obs_ts

    meter_ns = [0]
    hook = {"s": 0.0}

    def _metered(fn):
        def wrapper(*a, **k):
            t = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                hook["s"] += time.perf_counter() - t
        return wrapper

    _patched = [
        (obs_ts.StepSampler, "series"),        # end-of-solve flush
        (obs_anomaly.StallSentinel, "summary"),
        (tracing.Tracer, "emit"),              # every trace-sink write
    ]
    _saved = [(o, nm, getattr(o, nm)) for o, nm in _patched]

    def _hook_s() -> float:
        return hook["s"] + meter_ns[0] * 1e-9

    def run_once(enabled: bool) -> tuple:
        obs.set_enabled(enabled)
        tracing.configure(
            os.path.join(workdir, "bnb_trace.jsonl") if enabled else None
        )
        h0 = _hook_s()
        t0 = time.perf_counter()
        with tracing.span("bnb.solve", instance=inst.name):
            res = bb.solve(d, **kw)
        wall = time.perf_counter() - t0
        assert res.proven_optimal
        assert (res.series is not None) == enabled
        if enabled:
            run_once.res = res
        return wall, _hook_s() - h0

    try:
        obs_ts.StepSampler.METER_NS = meter_ns
        for obj, name, fn in _saved:
            setattr(obj, name, _metered(fn))
        # PAIRWISE interleaving with ALTERNATING ORDER: each pair's two
        # solves run back-to-back (immune to the minute-scale host drift
        # that swung the old per-arm-block ratio-of-medians by ±7%), and
        # the arm that goes first alternates between pairs — the second
        # slot of a pair is systematically faster on this host
        # (frequency ramp + cache warmth), which a fixed off-then-on
        # order would book entirely against the ON arm
        on_walls, off_walls, on_hooks = [], [], []
        for pair in range(2 * reps):
            if pair % 2 == 0:
                off_w, _ = run_once(False)
                on_w, on_h = run_once(True)
            else:
                on_w, on_h = run_once(True)
                off_w, _ = run_once(False)
            off_walls.append(off_w)
            on_walls.append(on_w)
            on_hooks.append(on_h)
    finally:
        obs_ts.StepSampler.METER_NS = None
        for obj, name, fn in _saved:
            setattr(obj, name, fn)
        obs.set_enabled(None)
        tracing.configure(None)
    on_ms = statistics.median(on_walls) * 1000.0
    off_ms = statistics.median(off_walls) * 1000.0
    # the GATED estimator: serial obs-code time over the non-obs wall,
    # per ON run, median across runs — each run self-normalizes, so host
    # speed drift between runs cancels instead of polluting the ratio
    per_run_pct = sorted(
        h / max(w - h, 1e-9) * 100.0 for w, h in zip(on_walls, on_hooks)
    )
    overhead_pct = statistics.median(per_run_pct)
    hook_ms = statistics.median(on_hooks) * 1000.0
    bnb_ok = overhead_pct <= 2.0
    # the A/B wall ratio, reported but NOT gated (see docstring): median
    # of per-pair ratios — each pair saw near-identical host conditions,
    # order effects cancel across the alternation, but residual pair
    # noise on a contended host still dwarfs a 2% signal
    pair_ratios = sorted(on_w / off_w for on_w, off_w in zip(on_walls, off_walls))
    wall_ratio_pct = (statistics.median(pair_ratios) - 1.0) * 100.0

    # -- serve trace completeness --------------------------------------------
    from tsp_mpi_reduction_tpu.serve.service import ServiceConfig, run_jsonl

    trace_path = os.path.join(workdir, "serve_trace.jsonl")
    tracing.configure(trace_path)
    rng = np.random.default_rng(7)
    lines = []
    for i in range(12):
        req = {"id": f"r{i}", "xy": (rng.random((8, 2)) * 50).tolist()}
        if i == 5:
            req["deadline_ms"] = 0.001  # degraded path must trace too
        lines.append(json.dumps(req))
    lines.insert(3, "this is not json")
    out = io.StringIO()
    try:
        svc = run_jsonl(lines, out, ServiceConfig(threads=4, max_wait_ms=1.0))
    finally:
        tracing.configure(None)
    responses = len(out.getvalue().strip().splitlines())
    spans = tracing.read_trace(trace_path)
    trees = tracing.build_trees(spans)
    orphans = tracing.orphan_spans(spans)
    roots = [
        n for t in trees.values() for n in t["roots"]
        if n["span"]["name"] == "serve.request"
    ]
    incomplete = [n for n in roots if not n["children"]]
    serve_ok = (
        responses == 13
        and len(roots) == 12  # the malformed line never becomes a request
        and not orphans
        and not incomplete
    )

    dispatches = int(getattr(run_once, "res").series["samples_total"])
    # marginal telemetry cost per host-loop dispatch, from the meter —
    # tracked as its own history metric so hook-cost creep (an added
    # registry call or host sync per dispatch is +1-10 us) is caught at
    # sub-us resolution, which no wall-based figure on this host can do
    us_per_dispatch = hook_ms * 1000.0 / max(dispatches, 1)
    artifact = {
        "metric": "obs_overhead",
        "unit": "pct",
        "instance": inst.name,
        "reps_per_arm": len(on_walls),
        "bnb": {
            "on_ms": round(on_ms, 3),
            "off_ms": round(off_ms, 3),
            "hook_ms": round(hook_ms, 3),
            "overhead_pct": round(overhead_pct, 2),
            "wall_ratio_pct": round(wall_ratio_pct, 2),
            "us_per_dispatch": round(us_per_dispatch, 3),
            "series_rows": dispatches,
            "estimator": "metered-hooks",
            "acceptance_max_pct": 2.0,
            "ok": bnb_ok,
        },
        "serve": {
            "requests": 12,
            "responses": responses,
            "spans": len(spans),
            "traces": len(trees),
            "request_roots": len(roots),
            "orphan_spans": len(orphans),
            "incomplete_trees": len(incomplete),
            "stats_health": json.loads(svc.stats_json())["health"],
            "ok": serve_ok,
        },
        "value": round(overhead_pct, 2),
        "vs_baseline": round(off_ms / on_ms, 4) if on_ms else None,
        "ok": bnb_ok and serve_ok,
        # ISSUE 9: the cost-capture evidence — flops/bytes/roofline for
        # every entry compiled through the cache this run (nonzero =
        # capture worked AND its cost is inside the <=2% budget above)
        "obs": {"device_costs": obs_costs.device_costs_block()},
    }
    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    hist_cfg = {
        "instance": inst.name, "reps": reps,
        "inner_steps": kw["inner_steps"], "pair_order": "alternating",
        "estimator": "metered-hooks",
    }
    _history_append("obs", artifact, config=hist_cfg)
    # second governed series: the per-dispatch marginal hook cost
    _history_append("obs", {
        "metric": "obs_us_per_dispatch",
        "value": round(us_per_dispatch, 3),
        "unit": "us",
        "ok": bnb_ok,
    }, config=hist_cfg)
    import shutil

    shutil.rmtree(workdir, ignore_errors=True)
    return 0 if artifact["ok"] else 1


def bench_shard() -> int:
    """Rank-resolved telemetry cost + skew evidence (ISSUE 10 acceptance).

    A 4-virtual-rank CPU mesh runs a deliberately SKEWED sharded solve —
    every root child seeded on rank 0 (``seed_mode="single-rank"``), ring
    balance with a tiny transfer slab so diffusion is slow (the VERDICT
    r4 stranded-rank regime) — with the rank-resolved telemetry layer
    fully on. Two things are measured:

    1. **Rank-hook cost** (the GATED figure, <= 2%): the serial time of
       the whole per-dispatch rank hook — ``RankSampler.due``'s counter
       compare, the once-per-window ``[R, K]`` stats-row collective +
       readback, the ring append + starvation check, and the end-of-run
       series/balance/gauge flushes — metered natively via
       ``RankSampler.METER_NS`` at the solver's own call site plus
       wrapped flush surfaces, over the solve's remaining wall. The
       serial-hook estimator from the PR 9 obs bench, NOT a wall A/B:
       back-to-back pair ratios of bit-identical solves swing
       0.66x-1.31x on this host, so a 2% wall gate would read scheduler
       noise.
    2. **Skew evidence**: the run must emit ``rank_series`` +
       ``rank_balance``, the per-rank sums must reconcile with the
       aggregate counters (nodes, spill bytes each way), and the starved
       rank(s) must be NAMED via ``rank_starvation`` events.

    Emits ``BENCH_SHARD_OBS.json`` (path: ``TSP_BENCH_SHARD_OUT``) and
    appends two governed history metrics (``shard_rank_obs_overhead``,
    ``shard_rank_us_per_dispatch``). Exit 1 when any criterion fails."""
    import statistics

    from tsp_mpi_reduction_tpu.utils.backend import force_host_platform

    ranks = int(os.environ.get("TSP_BENCH_SHARD_RANKS", "4"))
    force_host_platform(ranks)

    from tsp_mpi_reduction_tpu import obs
    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.obs import rankview as obs_rank
    from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    reps = int(os.environ.get("TSP_BENCH_SHARD_REPS", "5"))
    n = int(os.environ.get("TSP_BENCH_SHARD_N", "12"))
    cap = int(os.environ.get("TSP_BENCH_SHARD_CAPACITY", "160"))
    out_path = os.environ.get("TSP_BENCH_SHARD_OUT", "BENCH_SHARD_OBS.json")
    rng = np.random.default_rng(77)
    xy = rng.uniform(0, 100, (n, 2))
    d = np.rint(np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1)) * 10)
    mesh = make_rank_mesh(ranks)
    # min-out + no MST pruning keeps frontier pressure high (spill traffic
    # exists, so the per-rank byte attribution is exercised); single-rank
    # seeding + ring balance is the measured stranded-rank configuration
    kw = dict(
        capacity_per_rank=cap, k=4, inner_steps=2, bound="min-out",
        mst_prune=False, node_ascent=0, device_loop=False,
        seed_mode="single-rank", balance="ring", transfer=4,
        max_iters=2_000_000,
    )
    bb.solve_sharded(d, mesh, **kw)  # warm the XLA compiles

    # the hook meter: the per-dispatch/per-window path self-times through
    # the native METER_NS accumulator (billed at the solver's call site —
    # the collective dispatch lives outside the sampler class); the cold
    # once-per-solve flush surfaces are wrapped, where frame cost is noise
    meter_ns = [0]
    hook = {"s": 0.0}

    def _metered(fn):
        def wrapper(*a, **k):
            t = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                hook["s"] += time.perf_counter() - t
        return wrapper

    _patched = [
        (obs_rank.RankSampler, "series"),      # end-of-solve flush
        (obs_rank, "rank_balance"),            # imbalance accounting
        (obs_rank, "fold_rank_view"),          # registry gauge export
    ]
    _saved = [(o, nm, getattr(o, nm)) for o, nm in _patched]

    def _hook_s() -> float:
        return hook["s"] + meter_ns[0] * 1e-9

    walls, hooks = [], []
    last = None
    obs.set_enabled(True)
    try:
        obs_rank.RankSampler.METER_NS = meter_ns
        for obj, name, fn in _saved:
            setattr(obj, name, _metered(fn))
        for _rep in range(reps):
            h0 = _hook_s()
            t0 = time.perf_counter()
            res = bb.solve_sharded(d, mesh, **kw)
            walls.append(time.perf_counter() - t0)
            hooks.append(_hook_s() - h0)
            last = res
    finally:
        obs_rank.RankSampler.METER_NS = None
        for obj, name, fn in _saved:
            setattr(obj, name, fn)
        obs.set_enabled(None)

    assert last is not None and last.proven_optimal
    bal = last.rank_balance
    # per-run self-normalizing estimator (host drift between runs cancels)
    per_run_pct = sorted(
        h / max(w - h, 1e-9) * 100.0 for w, h in zip(walls, hooks)
    )
    overhead_pct = statistics.median(per_run_pct)
    hook_ms = statistics.median(hooks) * 1000.0
    dispatches = int(last.series["samples_total"]) if last.series else 1
    us_per_dispatch = hook_ms * 1000.0 / max(dispatches, 1)
    gate_ok = overhead_pct <= 2.0
    starve_events = [
        e for e in (last.anomalies or {}).get("events", [])
        if e.get("kind") == "rank_starvation"
    ]
    coherent = (
        last.rank_series is not None
        and bal is not None
        and sum(bal["nodes_per_rank"]) == last.nodes_expanded
        and sum(bal["spill_bytes_to_host_per_rank"]) == last.spill_bytes_to_host
        and sum(bal["spill_bytes_to_device_per_rank"])
        == last.spill_bytes_to_device
    )
    skew_named = bool(bal and bal["starved_ranks"]) and all(
        "rank" in e for e in starve_events
    )
    ok = gate_ok and coherent and skew_named
    artifact = {
        "metric": "shard_rank_obs_overhead",
        "unit": "pct",
        "value": round(overhead_pct, 2),
        "ranks": ranks,
        "n": n,
        "capacity_per_rank": cap,
        "reps": reps,
        "bnb": {
            "wall_ms": round(statistics.median(walls) * 1000.0, 3),
            "hook_ms": round(hook_ms, 3),
            "overhead_pct": round(overhead_pct, 2),
            "us_per_dispatch": round(us_per_dispatch, 3),
            "dispatches": dispatches,
            "rank_window": (
                last.rank_series["window"] if last.rank_series else None
            ),
            "rank_series_rows": (
                len(last.rank_series["rows"]) if last.rank_series else 0
            ),
            "estimator": "metered-hooks",
            "acceptance_max_pct": 2.0,
            "ok": gate_ok,
        },
        "rank_balance": bal,
        "starvation_events": len(starve_events),
        "starved_ranks": bal["starved_ranks"] if bal else [],
        "coherent": coherent,
        "ok": ok,
    }
    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    hist_cfg = {
        "ranks": ranks, "n": n, "capacity_per_rank": cap, "reps": reps,
        "seed_mode": kw["seed_mode"], "balance": kw["balance"],
        "estimator": "metered-hooks",
    }
    _history_append("shard", artifact, config=hist_cfg)
    # second governed series: marginal rank-hook cost per host dispatch
    _history_append("shard", {
        "metric": "shard_rank_us_per_dispatch",
        "value": round(us_per_dispatch, 3),
        "unit": "us",
        "ok": ok,
    }, config=hist_cfg)
    return 0 if ok else 1


def bench_balance() -> int:
    """Adaptive load-balance acceptance bench (ISSUE 15) ->
    ``BENCH_BALANCE.json``.

    The BENCH_SHARD_OBS skewed configuration — 4 virtual CPU ranks, every
    root child seeded on rank 0, tiny transfer slab — solved three ways:

    1. **static ring** (the VERDICT r4 stranded-rank regime): the seed's
       policy on the BENCH_SHARD_OBS config VERBATIM — including its
       4-row transfer slab — measuring baseline per-rank node imbalance
       (nodes max / max(min, 1)) and wall;
    2. **adaptive** (the tentpole): same instance and seeding, controller
       picks skip/pair/steal per round, with the mode's own DEFAULT
       donation-slab sizing (steal's one-collective fan-out needs a slab
       >= k*(ranks-1) to feed every starved rank from a lone donor;
       pinning it to the obs config's 4-row slab would amputate the very
       collective under test — the legs' ``transfer`` fields record the
       asymmetry). Gates: imbalance reduced >= 5x vs the ring at
       equal-or-better wall (noise-toleranced: back-to-back same-binary
       pair ratios swing ~0.7x-1.3x on shared hosts, so the wall gate
       uses medians with a 1.15x ceiling rather than reading scheduler
       noise as a regression), same proven-optimal cost and certified
       LB;
    3. **balanced control** (round-robin seeding, adaptive, on a
       rank-symmetric instance — a regular 12-gon ring plus a center
       city, so every rank's root subtrees are equivalent by the ring's
       symmetry and occupancy STAYS balanced; a random instance
       de-balances structurally mid-solve no matter how the roots are
       dealt): the controller must dispatch ZERO balance collectives
       while the skip dead-band is actually exercised — a balanced mesh
       pays nothing.

    Governed history series: ``shard_balance_imbalance`` (the adaptive
    leg's nodes max/min — the closed-loop flattening evidence) and
    ``shard_steal_bytes_per_node`` (moved bytes per expanded node — the
    repartition's traffic price, guarded against silent bloat)."""
    import statistics

    from tsp_mpi_reduction_tpu.utils.backend import force_host_platform

    ranks = int(os.environ.get("TSP_BENCH_BALANCE_RANKS", "4"))
    force_host_platform(ranks)

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.parallel.mesh import make_rank_mesh
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic

    reps = int(os.environ.get("TSP_BENCH_BALANCE_REPS", "5"))
    n = int(os.environ.get("TSP_BENCH_BALANCE_N", "12"))
    cap = int(os.environ.get("TSP_BENCH_BALANCE_CAPACITY", "160"))
    out_path = os.environ.get("TSP_BENCH_BALANCE_OUT", "BENCH_BALANCE.json")
    rng = np.random.default_rng(77)
    xy = rng.uniform(0, 100, (n, 2))
    d = np.rint(np.hypot(*(xy[:, None] - xy[None, :]).transpose(2, 0, 1)) * 10)
    # the balanced control's instance: vertex-transitive ring + center —
    # equivalent root subtrees per rank under round-robin dealing, with a
    # loose min-out floor (the center detour) so the search is real
    th = np.linspace(0, 2 * np.pi, 12, endpoint=False)
    xy_sym = np.concatenate(
        [np.stack([50 + 40 * np.cos(th), 50 + 40 * np.sin(th)], 1),
         [[50.0, 50.0]]]
    )
    d_sym = np.rint(
        np.hypot(*(xy_sym[:, None] - xy_sym[None, :]).transpose(2, 0, 1)) * 10
    )
    mesh = make_rank_mesh(ranks)
    kw = dict(
        capacity_per_rank=cap, k=4, inner_steps=2, bound="min-out",
        mst_prune=False, node_ascent=0, device_loop=False,
        seed_mode="single-rank", max_iters=2_000_000,
    )

    def _leg(balance: str, seed_mode: str, d_leg=None, transfer=None) -> dict:
        d_leg = d if d_leg is None else d_leg
        leg_kw = dict(kw, balance=balance, seed_mode=seed_mode,
                      transfer=transfer)
        bb.solve_sharded(d_leg, mesh, **leg_kw)  # warm the compiles
        walls, imbs, moved = [], [], []
        last = None
        for _rep in range(reps):
            t0 = time.perf_counter()
            res = bb.solve_sharded(d_leg, mesh, **leg_kw)
            walls.append(time.perf_counter() - t0)
            per = np.asarray(res.nodes_per_rank, np.float64)
            imbs.append(float(per.max() / max(per.min(), 1.0)))
            moved.append(int(res.balance["moved_rows_total"]))
            last = res
        assert last is not None
        return {
            "balance": balance,
            "seed_mode": seed_mode,
            "transfer": transfer,
            "wall_ms": round(statistics.median(walls) * 1000.0, 3),
            "imbalance": round(statistics.median(imbs), 3),
            "cost": last.cost,
            "proven_optimal": bool(last.proven_optimal),
            "lower_bound": last.lower_bound,
            "nodes": last.nodes_expanded,
            "moved_rows": int(statistics.median(moved)),
            "moved_bytes": int(
                statistics.median(moved) * last.balance["moved_bytes_total"]
                / max(last.balance["moved_rows_total"], 1)
            ),
            "collective_dispatches": last.balance["collective_dispatches"],
            "actions": last.balance["actions"],
            "switches": last.balance["switches"],
            "cv_max": last.balance["cv_max"],
        }

    # ring: the seed's BENCH_SHARD_OBS config verbatim (4-row slab);
    # adaptive: the mode's own default slab (fan-out-capable steal)
    ring = _leg("ring", "single-rank", transfer=4)
    ada = _leg("adaptive", "single-rank")
    flat = _leg("adaptive", "round-robin", d_leg=d_sym)

    reduction = ring["imbalance"] / max(ada["imbalance"], 1e-9)
    wall_ratio = ada["wall_ms"] / max(ring["wall_ms"], 1e-9)
    bytes_per_node = ada["moved_bytes"] / max(ada["nodes"], 1)
    gate_reduction = reduction >= 5.0
    gate_wall = wall_ratio <= 1.15
    gate_exact = (
        ada["proven_optimal"]
        and ring["proven_optimal"]
        and ada["cost"] == ring["cost"]
        and ada["lower_bound"] == ring["lower_bound"]
    )
    # zero collectives AND the dead-band actually exercised (skip chosen
    # at least once) — a run that proves before any decision would pass
    # the zero trivially without testing anything
    gate_flat = (
        flat["collective_dispatches"] == 0
        and flat["actions"].get("skip", 0) > 0
    )
    ok = gate_reduction and gate_wall and gate_exact and gate_flat
    artifact = {
        "metric": "shard_balance_imbalance",
        "unit": "ratio",
        "value": ada["imbalance"],
        "ranks": ranks,
        "n": n,
        "capacity_per_rank": cap,
        "reps": reps,
        "legs": {"ring": ring, "adaptive": ada, "balanced": flat},
        "imbalance_reduction": round(reduction, 2),
        "wall_ratio": round(wall_ratio, 3),
        "steal_bytes_per_node": round(bytes_per_node, 3),
        "gates": {
            "imbalance_reduction_min": 5.0,
            "imbalance_reduction_ok": gate_reduction,
            "wall_ratio_max": 1.15,
            "wall_ratio_ok": gate_wall,
            "exactness_ok": gate_exact,
            "balanced_zero_dispatches_ok": gate_flat,
        },
        "ok": ok,
    }
    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    hist_cfg = {
        "ranks": ranks, "n": n, "capacity_per_rank": cap, "reps": reps,
        "transfer": {leg["balance"]: leg["transfer"]
                     for leg in (ring, ada)},
        "estimator": "median-imbalance",
    }
    _history_append("balance", artifact, config=hist_cfg)
    # second governed series: the repartition's traffic price per node
    _history_append("balance", {
        "metric": "shard_steal_bytes_per_node",
        "value": round(bytes_per_node, 3),
        "unit": "bytes",
        "ok": ok,
    }, config=hist_cfg)
    return 0 if ok else 1


def bench_fleet() -> int:
    """Fleet serving acceptance bench (ISSUE 11) -> ``BENCH_FLEET.json``.

    Three measurements through the real front + serve-replica-subprocess
    stack on one shared cache tier + one fleet compile cache:

    1. **clean sweep** — sustained RPS and p50/p99 front-measured latency
       vs replica count 1/2/4 on a same-shape generous-deadline workload
       (warmed outside the timed window; replica startup excluded);
    2. **chaos sweep** — the SAME workload and replica counts with a
       ``replica.kill`` injected mid-flight per leg: answered-exactly-once
       rate, restarts, re-dispatches, degraded answers per leg;
    3. **acceptance demo** — >= 3 replicas serving >= 48 mixed-deadline
       requests (tight + generous + permuted/translated resubmissions)
       while ``TSP_FAULTS`` kills AND hangs replicas, with the span-trace
       sink on: asserts 100% answered exactly once with VALID tours,
       cross-replica shared-cache hits, restarts + re-dispatches visible
       in health counters, and one stitched trace per request with zero
       orphan spans.

    The governed history metric is the demo's answered-exactly-once rate
    — a COUNTER estimator (host noise makes <5% wall gates unmeasurable
    here; BENCHMARKS.md); RPS/p99 ride the artifact unguarded.
    """
    import io
    import tempfile

    from tsp_mpi_reduction_tpu.fleet import FleetConfig, FleetFront
    from tsp_mpi_reduction_tpu.fleet.supervisor import SupervisorConfig
    from tsp_mpi_reduction_tpu.obs import tracing as _btracing
    from tsp_mpi_reduction_tpu.resilience import faults as _bfaults
    from tsp_mpi_reduction_tpu.resilience.checkpoint import write_json_atomic
    from tsp_mpi_reduction_tpu.resilience.health import HEALTH as _BHEALTH
    from tsp_mpi_reduction_tpu.serve.service import run_jsonl

    n = int(os.environ.get("TSP_BENCH_FLEET_N", "8"))
    sweep_reqs = int(os.environ.get("TSP_BENCH_FLEET_REQS", "32"))
    demo_reqs = max(int(os.environ.get("TSP_BENCH_FLEET_DEMO_REQS", "48")), 48)
    backend = os.environ.get("TSP_BENCH_FLEET_BACKEND", "cpu")
    out_path = os.environ.get("TSP_BENCH_FLEET_OUT", "BENCH_FLEET.json")
    work_root = tempfile.mkdtemp(prefix="tsp_bench_fleet_")
    compile_cache = os.path.join(work_root, "compile_cache")
    rng = np.random.default_rng(17)

    def fleet_cfg(replicas: int, shared_dir: str) -> FleetConfig:
        return FleetConfig(
            replicas=replicas,
            threads=max(8, replicas * 4),
            replica_threads=4,
            shared_cache_dir=shared_dir,
            compile_cache_dir=compile_cache,
            backend=backend,
            default_deadline_ms=20_000.0,
            # generous per-hop wait: re-dispatch off a dead/wedged
            # replica is driven by the supervisor's death abort (fast),
            # not this timeout — a short hop timeout would instead race
            # cold first-compiles into spurious re-dispatches
            hop_timeout_s=12.0,
            dispatch_attempts=4,
            supervisor=SupervisorConfig(
                probe_interval_s=0.1,
                wedge_timeout_s=2.0,
                startup_grace_s=3.0,
                restart_backoff_base_s=0.2,
                restart_backoff_max_s=1.0,
                healthy_reset_s=5.0,
            ),
        )

    def make_requests(count, uniques, tight_every=0):
        """``uniques`` fresh instances cycled with permuted+translated
        resubmissions (the cross-replica cache-hit traffic); every
        ``tight_every``-th request gets a 50 ms deadline instead of the
        generous default."""
        instances = [rng.uniform(0, 1000, (n, 2)) for _ in range(uniques)]
        reqs = []
        for i in range(count):
            base = instances[i % uniques]
            if i < uniques:
                xy = base
            else:  # resubmission: same instance, permuted + translated
                xy = base[rng.permutation(n)] + float(rng.integers(-400, 400))
            deadline = (
                50.0 if (tight_every and i % tight_every == tight_every - 1)
                else 20_000.0
            )
            reqs.append(
                {"id": f"q{i}", "xy": xy.tolist(), "deadline_ms": deadline}
            )
        return reqs

    def run_leg(replicas, requests, chaos_spec=None, trace_path=None):
        shared_dir = os.path.join(work_root, f"shared_r{replicas}_{bool(chaos_spec)}")
        if trace_path:
            _btracing.configure(trace_path)
        front = FleetFront(fleet_cfg(replicas, shared_dir))
        try:
            # warm OUTSIDE the timed window: replica startup + the first
            # pipeline-rung compile (amortized fleet-wide by the shared
            # TSP_COMPILE_CACHE) are one-time costs, not steady state
            warm = [
                {"id": f"w{i}", "xy": rng.uniform(0, 1000, (n, 2)).tolist(),
                 "deadline_ms": 60_000.0}
                for i in range(max(replicas * 2, 2))
            ]
            warm_out = io.StringIO()
            run_jsonl([json.dumps(r) + "\n" for r in warm], warm_out, service=front)
            health0 = _BHEALTH.snapshot()
            if chaos_spec:
                _bfaults.configure(chaos_spec)
            t0 = time.perf_counter()
            out = io.StringIO()
            run_jsonl(
                [json.dumps(r) + "\n" for r in requests], out, service=front
            )
            wall = time.perf_counter() - t0
            _bfaults.clear()
            stats = json.loads(front.stats_json())
        finally:
            _bfaults.clear()
            front.close()
            if trace_path:
                _btracing.configure(None)
        responses = [json.loads(ln) for ln in out.getvalue().strip().splitlines()]
        lat = sorted(
            r.get("fleet_latency_ms", 0.0) for r in responses if "error" not in r
        )
        ids = [r.get("id") for r in responses]
        valid = 0
        for r in responses:
            tour = r.get("tour") or []
            if (
                "error" not in r
                and tour
                and tour[0] == tour[-1]
                and sorted(tour[:-1]) == list(range(n))
            ):
                valid += 1
        health = _BHEALTH.delta_since(health0)
        leg = {
            "replicas": replicas,
            "requests": len(requests),
            "answered": len(responses),
            "answered_exactly_once": len(ids) == len(set(ids)) == len(requests),
            "valid_tours": valid,
            "rps": round(len(requests) / wall, 2),
            "p50_ms": round(lat[len(lat) // 2], 2) if lat else None,
            "p99_ms": round(lat[max(int(0.99 * (len(lat) - 1)), 0)], 2) if lat else None,
            "wall_s": round(wall, 2),
            "restarts": health.get("fleet_replica_restarts", 0),
            "redispatches": health.get("fleet_redispatches", 0),
            "degraded_answers": health.get("fleet_degraded_answers", 0),
            "stats_fleet": {
                k: stats["fleet"][k]
                for k in (
                    "restarts_total", "redispatches_total",
                    "degraded_answers", "duplicates_suppressed",
                )
            },
            "replica_scrapes": [
                row.get("scrape") for row in stats["fleet"]["replicas"]
            ],
            "shared_cache_fleetwide": _sum_replica_shared(stats),
            "cache_hits": sum(
                1 for r in responses if r.get("cache") == "hit"
            ),
        }
        return leg, responses, stats

    def _sum_replica_shared(stats):
        out = {"shared_cache_hits": 0, "shared_cache_publishes": 0}
        for row in stats["fleet"]["replicas"]:
            scrape = row.get("scrape") or {}
            for k in out:
                out[k] += int(scrape.get(k, 0))
        return out

    print("fleet bench: clean sweep", file=sys.stderr)
    sweep = []
    for r in (1, 2, 4):
        leg, _, _ = run_leg(r, make_requests(sweep_reqs, sweep_reqs))
        print(f"  clean r={r}: {leg['rps']} rps p99 {leg['p99_ms']} ms",
              file=sys.stderr)
        sweep.append(leg)

    print("fleet bench: chaos sweep (replica.kill mid-flight)", file=sys.stderr)
    chaos_sweep = []
    for r in (1, 2, 4):
        leg, _, _ = run_leg(
            r, make_requests(sweep_reqs, sweep_reqs),
            chaos_spec="replica.kill:raise,nth=6",
        )
        print(
            f"  chaos r={r}: {leg['rps']} rps p99 {leg['p99_ms']} ms "
            f"restarts {leg['restarts']} redispatches {leg['redispatches']} "
            f"degraded {leg['degraded_answers']}",
            file=sys.stderr,
        )
        chaos_sweep.append(leg)

    # -- acceptance demo: >=3 replicas, >=48 mixed-deadline requests,
    # kills AND hangs mid-flight, stitched traces on
    print("fleet bench: chaos acceptance demo", file=sys.stderr)
    trace_path = os.path.join(work_root, "fleet_demo_trace.jsonl")
    demo_requests = make_requests(
        demo_reqs, uniques=demo_reqs // 2, tight_every=4
    )
    demo, demo_responses, demo_stats = run_leg(
        3, demo_requests,
        chaos_spec="replica.kill:raise,nth=10;replica.kill:raise,nth=30;"
        "replica.hang:raise,nth=20",
        trace_path=trace_path,
    )
    spans = _btracing.read_trace(trace_path)
    trees = _btracing.build_trees(spans)
    orphans = _btracing.orphan_spans(spans)
    fleet_roots = sum(
        1
        for t in trees.values()
        for root in t["roots"]
        if root["span"]["name"] == "fleet.request"
        and str(root["span"]["attrs"].get("id", "")).startswith("q")
    )
    demo["trace"] = {
        "spans": len(spans),
        "traces": len(trees),
        "fleet_request_roots": fleet_roots,
        "orphans": len(orphans),
    }
    answered_rate = (
        demo["valid_tours"] / demo["requests"]
        if demo["answered_exactly_once"]
        else 0.0
    )
    ok = (
        demo["answered_exactly_once"]
        and demo["valid_tours"] == demo["requests"]
        and demo["restarts"] >= 1
        and demo["redispatches"] >= 1
        and demo["shared_cache_fleetwide"]["shared_cache_hits"] >= 1
        and fleet_roots == demo["requests"]
        and len(orphans) == 0
        and all(leg["answered_exactly_once"] for leg in sweep + chaos_sweep)
    )
    artifact = {
        "metric": "fleet_chaos_answered_rate",
        "value": round(answered_rate, 4),
        "unit": "fraction",
        "n": n,
        "backend": backend,
        "sweep": sweep,
        "chaos_sweep": chaos_sweep,
        "demo": demo,
        "ok": bool(ok),
    }
    write_json_atomic(out_path, artifact)
    print(json.dumps(artifact))
    _history_append(
        "fleet", artifact,
        config={"n": n, "requests": demo_reqs, "replicas": 3,
                "estimator": "answered-exactly-once-counter"},
    )
    import shutil

    shutil.rmtree(work_root, ignore_errors=True)  # 7 legs of cache trees
    return 0 if ok else 1


def main() -> int:
    if os.environ.get("TSP_BENCH") == "compile-child":
        # one measured subprocess of the compile bench (selects its own
        # backend; the parent passes TSP_BACKEND_PROBED after child 1)
        return bench_compile_child()
    if os.environ.get("TSP_BENCH") == "compile":
        # parent spawner only — must not initialize a jax backend (the
        # remote-TPU claim is exclusive per process; children claim it)
        return bench_compile()
    if os.environ.get("TSP_BENCH") == "step-child":
        # one measured kernel leg (selects its own backend)
        return bench_step_child()
    if os.environ.get("TSP_BENCH") == "step":
        # parent spawner only — children claim the (exclusive) accelerator
        return bench_step()
    if os.environ.get("TSP_BENCH") == "spill":
        # forces its own CPU virtual mesh — never probes the accelerator
        return bench_spill()
    if os.environ.get("TSP_BENCH") == "shard":
        # forces its own CPU virtual mesh — never probes the accelerator
        return bench_shard()
    if os.environ.get("TSP_BENCH") == "balance":
        # forces its own CPU virtual mesh — never probes the accelerator
        return bench_balance()
    if os.environ.get("TSP_BENCH") == "fleet":
        # front-process orchestration only: the replicas are subprocesses
        # that select their own backend (default cpu; the parent must not
        # claim an exclusive accelerator its replicas then cannot share)
        return bench_fleet()
    if os.environ.get("TSP_BENCH") == "faults":
        # host-side checkpoint IO — never probes the accelerator
        from tsp_mpi_reduction_tpu.utils.backend import select_backend

        select_backend("cpu")
        return bench_faults()
    if os.environ.get("TSP_BENCH") == "obs":
        # host-side instrumentation pricing — never probes the accelerator
        from tsp_mpi_reduction_tpu.utils.backend import select_backend

        select_backend("cpu")
        return bench_obs()
    if (
        os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
        or os.environ.get("TSP_BENCH_PROBED") == "1"
    ):
        pass  # CPU pinned, or the parent bench already probed
    elif not _accelerator_usable():
        print(
            "bench: no usable accelerator; falling back to CPU "
            "(numbers will not reflect TPU performance)",
            file=sys.stderr,
        )
        from tsp_mpi_reduction_tpu.utils.backend import select_backend

        select_backend("cpu")

    serve_mode = os.environ.get("TSP_BENCH") == "serve"
    bnb_mode = os.environ.get("TSP_BENCH", "pipeline") == "bnb"
    quick = (
        "--quick" in sys.argv[1:] or os.environ.get("TSP_BENCH_QUICK") == "1"
    )
    fold_pin = os.environ.get("TSP_BENCH_FOLD")
    if not bnb_mode and fold_pin is not None and fold_pin not in VALID_FOLDS:
        print(
            f"bench: ignoring unrecognized TSP_BENCH_FOLD={fold_pin!r} "
            f"(expected one of {VALID_FOLDS}); measuring all",
            file=sys.stderr,
        )
        fold_pin = None
    if not bnb_mode and not serve_mode and fold_pin is None:
        # PARENT SPAWNER: each fold is measured in its own subprocess
        # (see the methodology comment below). The parent must NOT
        # initialize a jax backend — the remote-TPU claim is exclusive
        # per process, so a parent holding it would deadlock every child.
        return _spawn_fold_children(quick=quick)

    from tsp_mpi_reduction_tpu.utils.backend import enable_persistent_cache

    import jax

    enable_persistent_cache(jax.default_backend())

    if serve_mode:
        return bench_serve()
    if bnb_mode:
        return bench_bnb()
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.ops import held_karp
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix
    from tsp_mpi_reduction_tpu.ops.generator import generate_instance
    from tsp_mpi_reduction_tpu.ops.held_karp import build_plan, solve_blocks_from_dists
    from tsp_mpi_reduction_tpu.ops.local_search import polish, tour_length
    from tsp_mpi_reduction_tpu.ops.merge import (
        fold_tours,
        fold_tours_tree,
        fold_tours_tree_xy,
    )

    impl = os.environ.get("TSP_TPU_IMPL")  # compact|dense|fused|pallas
    if impl:
        held_karp.set_impl(impl)
        print(f"bench impl override: {impl}", file=sys.stderr)

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    _, xy = generate_instance(N, BLOCKS, GRID, GRID)
    xy32 = jnp.asarray(np.asarray(xy, np.float32))

    def make_step(fold, from_xy, do_polish):
        total = N * BLOCKS

        @jax.jit
        def step(xy_blocks, feedback):
            flat = xy_blocks.reshape(-1, 2)
            block_d = jax.vmap(distance_matrix)(xy_blocks)
            costs, local_tours = solve_blocks_from_dists(block_d, jnp.float32)
            offsets = (jnp.arange(BLOCKS, dtype=jnp.int32) * N)[:, None]
            ctx = flat if from_xy else distance_matrix(flat)
            ids, length, cost = fold(
                local_tours.astype(jnp.int32) + offsets, costs, ctx
            )
            # measured true length alongside the reference-semantics
            # formulaic cost (quirk #4: the splice is never re-measured)
            dist = ctx if not from_xy else distance_matrix(flat)
            t_open = ids[:total]  # drop the closing duplicate
            if do_polish:
                t_open, _ = polish(t_open, dist, max_rounds=POLISH_MAX_ROUNDS)
            measured = tour_length(t_open, dist)
            head = measured if do_polish else cost
            # feedback*0 threads the previous run's output into this run's
            # input: the M timed runs form one dependency chain, so a
            # single final readback drains them all (see module docstring)
            return head + feedback * 0.0, cost, measured
        return step

    def timed(name, fold, m, from_xy=False, do_polish=False):
        step = make_step(fold, from_xy, do_polish)
        t0 = time.perf_counter()
        c, _, _ = step(xy32, jnp.float32(0.0))  # compile+first run; no readback
        # block_until_ready does NOT block in the relay's fast mode, and
        # any true sync is a device->host transfer that would poison every
        # subsequent dispatch — so the warmup run's execution tail can
        # spill into the timed window below. The bias is bounded (<=1/m of
        # the window, shrinking with m) and conservative: it can only
        # OVERSTATE per-run time, never flatter it.
        jax.block_until_ready(c)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(m):
            c, cost, measured = step(xy32, c)
        v = float(c)  # ONE readback: drains the chained queue
        per_run = (time.perf_counter() - t0) * 1000.0 / m
        return per_run, v, compile_s, float(cost), float(measured)

    # CHILD: measure the one fold this process is pinned to (see
    # _spawn_fold_children for why folds are process-isolated): the tree
    # (log2(B) vmapped merge rounds — the shape of the reference's own
    # cross-rank reduce) removes the B-step sequential dependency chain;
    # tree_xy computes the swap costs from coordinates (no [N,N] random
    # gathers; same values as tree on CPU, ±1 ULP under TPU fusion — the
    # cost is printed so a flip is visible); the scan is the reference's
    # rank-local fold order. The merge operator is non-associative, so
    # tree and scan costs legitimately differ — exactly as the
    # reference's output differs across rank counts.
    folds = {
        "tree_xy": (fold_tours_tree_xy, True, False),
        "tree": (fold_tours_tree, False, False),
        "scan": (fold_tours, False, False),
        "tree_xy_polish": (fold_tours_tree_xy, True, True),
    }
    assert tuple(folds) == VALID_FOLDS  # parent/child fold sets in sync
    # chained-run count: bias <= 1/m, see timed(). CPU fallback shrinks the
    # averaging window (each chained run is ~20 s there, BENCH_r05) so a
    # full fold sweep fits any sane driver timeout; the per-run number is
    # unchanged. An explicit TSP_BENCH_REPS always wins.
    m_env = os.environ.get("TSP_BENCH_REPS")
    m = int(m_env) if m_env else (3 if dev.platform == "cpu" else 20)
    fold, from_xy, do_polish = folds[fold_pin]
    ms, v, cs, cost, measured = timed(
        fold_pin, fold, m, from_xy=from_xy, do_polish=do_polish
    )
    print(
        f"{fold_pin}: {ms:.1f} ms/run over {m} chained runs "
        f"(compile+first {cs:.1f}s, cost={cost:.3f}, measured={measured:.3f})",
        file=sys.stderr,
    )
    plan = build_plan(N)
    nodes_per_sec = plan.dp_transitions * BLOCKS / (ms / 1000.0)
    print(f"dp_transitions/s={nodes_per_sec:.3e}", file=sys.stderr)
    print(_pipeline_json(ms, fold_pin, cost=v, measured=measured))
    return 0


def _pipeline_json(
    value_ms: float, fold: str, cost: float | None = None,
    folds: dict | None = None, measured: float | None = None,
) -> str:
    """One-line artifact. ``cost`` is the reported fold's headline cost
    (formulaic reference semantics for plain folds — quirk #4 — but the
    MEASURED length for the polish fold, whose point is true quality);
    ``measured`` is always the re-measured length of the final tour;
    ``folds`` carries every measured fold's {ms, cost, measured} so the
    speed/quality trade-off is in the JSON itself, not just stderr.
    Baseline cost for this instance: 34367.05 (the reference's own
    single-rank fold order, BASELINE.md 16x100 row)."""
    out = {
        "metric": "pipeline_16x100_wall_ms",
        "value": round(value_ms, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / value_ms, 2),
        "fold": fold,
    }
    if cost is not None:
        out["cost"] = round(cost, 3)
        out["baseline_cost"] = 34367.048
    if measured is not None:
        out["measured"] = round(measured, 3)
    if folds is not None:
        out["folds"] = folds
    return json.dumps(out)


def _spawn_fold_children(quick: bool = False) -> int:
    """Measure every fold shape, each in its own subprocess, and report
    the fastest. Process isolation matters twice on the remote relay:
    a process's first readback permanently degrades its later dispatches
    (so folds measured after another fold's drain would be biased), and
    the chip claim is exclusive per process (so this parent must never
    initialize a jax backend itself — children would deadlock).

    The sweep runs under a WALL BUDGET (``TSP_BENCH_BUDGET_S``, default
    600 s, measured from process start so the accelerator probe counts):
    each child gets at most the remaining budget, folds that no longer
    fit are skipped with a stderr note, and a JSON line is ALWAYS printed
    — the round-5 driver blackout (rc=124, ``parsed: null``) was exactly
    an external timeout landing mid-child with nothing emitted.
    ``quick``: restrict to the two cheap-compile folds (tree/scan; the
    xy variants pay a ~4x compile on CPU). The CPU-fallback shrink of the
    per-fold chained-run count happens CHILD-side (each child knows its
    own resolved backend — see the ``m_env`` default in the child path)."""
    import subprocess

    budget = float(os.environ.get("TSP_BENCH_BUDGET_S", "600"))
    deadline = _T0 + budget
    folds = ("tree", "scan") if quick else VALID_FOLDS
    results = {}
    skipped = []
    for nm in folds:
        remaining = deadline - time.monotonic()
        if remaining < 30.0:
            skipped.append(nm)
            print(
                f"bench: skipping fold {nm} — {remaining:.0f}s left of the "
                f"{budget:.0f}s budget", file=sys.stderr,
            )
            continue
        env = dict(os.environ, TSP_BENCH_FOLD=nm, TSP_BENCH_PROBED="1")
        if quick and "TSP_BENCH_REPS" not in env:
            env["TSP_BENCH_REPS"] = "2"
        if env.get("JAX_PLATFORMS", "").strip() == "cpu":
            # CPU fallback: the axon sitecustomize would re-register the
            # remote plugin in the child and dial the dead tunnel anyway
            # (it overrides JAX_PLATFORMS) — disarm it entirely
            env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env,
                timeout=min(1200.0, remaining),
            )
        except subprocess.TimeoutExpired:
            # a lapsed chip grant hangs a fresh client init forever; a
            # slow CPU fold can also outlive its budget slice
            print(f"bench: fold {nm} subprocess timed out", file=sys.stderr)
            continue
        sys.stderr.write(r.stderr)
        try:
            child = json.loads(r.stdout.strip().splitlines()[-1])
            results[nm] = {
                "ms": float(child["value"]),
                "cost": child.get("cost"),
                "measured": child.get("measured"),
            }
        except (json.JSONDecodeError, IndexError, KeyError):
            print(f"bench: fold {nm} subprocess failed "
                  f"(rc={r.returncode})", file=sys.stderr)
    if not results:
        # STILL emit a parsed JSON line — a driver must never see rc!=0
        # with nothing to parse (the BENCH_r05 blackout shape). Blame the
        # budget only for folds it actually skipped; the rest failed or
        # timed out on their own (details on stderr above).
        attempted = [nm for nm in folds if nm not in skipped]
        print(json.dumps({
            "metric": "pipeline_16x100_wall_ms",
            "value": None,
            "unit": "ms",
            "error": (
                f"no fold completed within the {budget:.0f}s budget"
                if skipped and not attempted
                else "every attempted fold failed or timed out "
                     "(see stderr); " + (
                         f"{len(skipped)} fold(s) budget-skipped"
                         if skipped else "none budget-skipped"
                     )
            ),
            "failed_folds": attempted,
            "skipped_folds": list(skipped),
        }))
        return 1
    best = min(results, key=lambda nm: results[nm]["ms"])
    line = _pipeline_json(
        results[best]["ms"], best, cost=results[best]["cost"],
        folds=results, measured=results[best].get("measured"),
    )
    print(line)
    # parent-side history append (children print only — one record per
    # sweep, keyed on the fold set so quick/full sweeps never compare)
    _history_append("pipeline", json.loads(line), config={
        "folds": sorted(results), "quick": quick,
        "n": N, "blocks": BLOCKS,
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())

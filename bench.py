"""Benchmark driver. Prints ONE JSON line: {"metric","value","unit","vs_baseline"}.

Two modes, selected by ``TSP_BENCH`` (default ``pipeline``):

- ``pipeline`` — full blocked pipeline, 16 cities x 100 blocks (headline
  config). Baseline: the unmodified reference solving the same
  deterministic instance single-rank takes 69997 ms (BASELINE.md, measured
  in this environment at g++ -O2; identical instance because generation is
  srand(0)-deterministic). ``vs_baseline`` = baseline_ms / ours.
  Method: device pipeline in float32 (TPU speed mode) — on-device distance
  matrix, vmapped dense Held-Karp over all 100 blocks, then the merge
  fold. BOTH fold shapes are measured and the faster is reported
  (disclosed via the JSON ``fold`` key): the log2(B) TREE of vmapped
  pairwise merges (fold_tours_tree — the shape of the reference's own
  cross-rank MPI_ManualReduce; the merge operator is non-associative, so
  the folded cost legitimately differs from the sequential within-rank
  fold exactly as the reference's output differs across rank counts) and
  the sequential scan fold the r01/r02 benches used.
  ``TSP_BENCH_FOLD=scan|tree`` pins one. Each is compiled once (warmup),
  then the median of 3 timed end-to-end executions counts.

- ``bnb`` — the north-star metric (BASELINE.json): B&B nodes/sec on a
  TSPLIB instance solved to PROVEN optimality. Default instance: eil51
  (426) — berlin52's Held-Karp root bound equals its optimum, so with the
  ILS incumbent it closes at the root in 1 node and has no throughput to
  measure; eil51's bound genuinely gaps (~422.5 vs 426), forcing a real
  ~500k-node search. The reference has no B&B and no TSPLIB mode
  (SURVEY.md §0 discrepancy note), so there is no reference binary to
  time; the baseline anchor is this engine's own single-rank CPU rate
  x8 — a stand-in for the north star's "8-rank MPI" comparison that
  generously assumes perfect MPI scaling (BNB_CPU_8RANK_ANCHOR below,
  measured on this host). ``vs_baseline`` = device nodes/sec / anchor.
  Warmup excludes compile from the timed run.

Compile time is excluded in both modes (the reference has no JIT; with the
persistent compilation cache it is a one-time cost) and printed to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_MS = 69997.0  # BASELINE.md: 16 cities/block x 100 blocks, 1 rank
N, BLOCKS, GRID = 16, 100, 1000

#: Single-rank CPU B&B nodes/sec on eil51 (this engine, this host, k=256,
#: proven-optimal run, compile excluded) x 8 ranks — i.e. the anchor
#: generously assumes perfect 8-way MPI scaling of our own CPU rate.
#: Measured 2026-07-30 at the default engine config (node_ascent=2):
#: 7,730 nodes/s, proof in 28.1 s at capacity 1<<17; see BENCHMARKS.md.
BNB_CPU_8RANK_ANCHOR = 8 * 7730.0


def _accelerator_usable(timeout_s: float = 180.0) -> bool:
    """Probe accelerator init in a subprocess (it can hang on a dead tunnel).

    The remote-TPU ("axon") backend's first client creation performs a
    claim/grant handshake that blocks indefinitely when no chip is currently
    granted to this container; a subprocess probe with a timeout turns that
    hang into a clean CPU fallback.
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        if r.returncode == 0 and "ok" in r.stdout:
            return True
        print(
            f"bench: accelerator probe exited rc={r.returncode}: "
            f"{(r.stderr or r.stdout).strip()[-300:]}",
            file=sys.stderr,
        )
        return False
    except subprocess.TimeoutExpired:
        print(
            f"bench: accelerator init timed out after {timeout_s:.0f}s "
            "(claim/grant handshake never completed)",
            file=sys.stderr,
        )
        return False


def bench_bnb() -> int:
    """North-star metric: B&B nodes/sec to proven optimality (default
    instance eil51 — see module docstring for why not berlin52)."""
    import jax

    from tsp_mpi_reduction_tpu.models import branch_bound as bb
    from tsp_mpi_reduction_tpu.utils import tsplib

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)
    name = os.environ.get("TSP_BENCH_INSTANCE", "eil51")
    inst = tsplib.embedded(name)
    d = inst.distance_matrix()
    k = int(os.environ.get("TSP_BENCH_K", "256"))
    # per-node mini-ascent depth: more steps = fewer nodes but more Prims
    # per pop; the best time-to-proof point is hardware-dependent
    na = int(os.environ.get("TSP_BENCH_NODE_ASCENT", "2"))

    t0 = time.perf_counter()
    bb.solve(d, capacity=1 << 17, k=k, inner_steps=8, max_iters=8, node_ascent=na)
    print(f"warmup (compile): {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    res = bb.solve(
        d, capacity=1 << 17, k=k, inner_steps=8, time_limit_s=600, node_ascent=na
    )
    ok = res.proven_optimal and res.cost == inst.known_optimum
    print(
        f"{name}: cost={res.cost} (known {inst.known_optimum}) "
        f"proven={res.proven_optimal} nodes={res.nodes_expanded} "
        f"wall={res.wall_seconds:.2f}s time_to_best={res.time_to_best:.2f}s",
        file=sys.stderr,
    )
    if not ok:
        print("bench: WARNING — run did not prove the known optimum", file=sys.stderr)
    value = res.nodes_per_sec
    print(
        json.dumps(
            {
                "metric": f"bnb_{name}_nodes_per_sec",
                "value": round(value, 1),
                "unit": "nodes/s",
                "vs_baseline": round(value / BNB_CPU_8RANK_ANCHOR, 2),
            }
        )
    )
    return 0


def main() -> int:
    if not _accelerator_usable():
        print(
            "bench: no usable accelerator; falling back to CPU "
            "(numbers will not reflect TPU performance)",
            file=sys.stderr,
        )
        from tsp_mpi_reduction_tpu.utils.backend import select_backend

        select_backend("cpu")

    from tsp_mpi_reduction_tpu.utils.backend import enable_persistent_cache

    import jax

    enable_persistent_cache(jax.default_backend())

    if os.environ.get("TSP_BENCH", "pipeline") == "bnb":
        return bench_bnb()
    import jax.numpy as jnp

    from tsp_mpi_reduction_tpu.ops import held_karp
    from tsp_mpi_reduction_tpu.ops.distance import distance_matrix
    from tsp_mpi_reduction_tpu.ops.generator import generate_instance
    from tsp_mpi_reduction_tpu.ops.held_karp import build_plan, solve_blocks_from_dists
    from tsp_mpi_reduction_tpu.ops.merge import fold_tours, fold_tours_tree

    impl = os.environ.get("TSP_TPU_IMPL")  # compact|dense|fused|pallas
    if impl:
        held_karp.set_impl(impl)
        print(f"bench impl override: {impl}", file=sys.stderr)

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    _, xy = generate_instance(N, BLOCKS, GRID, GRID)
    xy32 = np.asarray(xy, np.float32)

    def make_step(fold):
        @jax.jit
        def step(xy_blocks):
            flat = xy_blocks.reshape(-1, 2)
            dist = distance_matrix(flat)
            block_d = jax.vmap(distance_matrix)(xy_blocks)
            costs, local_tours = solve_blocks_from_dists(block_d, jnp.float32)
            offsets = (jnp.arange(BLOCKS, dtype=jnp.int32) * N)[:, None]
            ids, length, cost = fold(
                local_tours.astype(jnp.int32) + offsets, costs, dist
            )
            return cost, length

        return step

    def timed(name, fold):
        step = make_step(fold)
        t0 = time.perf_counter()
        cost, _ = step(jnp.asarray(xy32))
        cost.block_until_ready()
        print(
            f"{name}: first call (compile+run) {time.perf_counter() - t0:.1f}s, "
            f"cost={float(cost):.3f}",
            file=sys.stderr,
        )
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            cost, _ = step(jnp.asarray(xy32))
            cost.block_until_ready()
            times.append((time.perf_counter() - t0) * 1000.0)
        med = float(np.median(times))
        print(f"{name}: times_ms={['%.1f' % t for t in times]}", file=sys.stderr)
        return med

    # measure BOTH fold shapes and report the faster (disclosed via the
    # "fold" key): the tree (log2(B) vmapped merge rounds — the shape of
    # the reference's own cross-rank reduce) removes the B-step sequential
    # dependency chain; the scan is the r01/r02 method. The merge operator
    # is non-associative, so their costs legitimately differ — exactly as
    # the reference's output differs across rank counts.
    # TSP_BENCH_FOLD=scan|tree pins one.
    pin = os.environ.get("TSP_BENCH_FOLD")
    if pin not in (None, "tree", "scan"):
        print(
            f"bench: ignoring unrecognized TSP_BENCH_FOLD={pin!r} "
            "(expected 'tree' or 'scan'); measuring both",
            file=sys.stderr,
        )
        pin = None
    results = {}
    if pin in (None, "tree"):
        results["tree"] = timed("tree", fold_tours_tree)
    if pin in (None, "scan"):
        results["scan"] = timed("scan", fold_tours)
    best = min(results, key=results.get)
    value = results[best]
    plan = build_plan(N)
    nodes_per_sec = plan.dp_transitions * BLOCKS / (value / 1000.0)
    print(f"dp_transitions/s={nodes_per_sec:.3e}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "pipeline_16x100_wall_ms",
                "value": round(value, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / value, 2),
                "fold": best,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
